"""Contrastive training for the embedder — the framework's full train step.

The reference never trains models (SURVEY.md §5.7); this is TPU-first new
design: in-batch-negative InfoNCE over (query, positive-doc) pairs, the
standard recipe behind the retrieval encoders the RAG stack serves. The step
is jit-compiled over the mesh with data-parallel batches, tensor-parallel
weights (encoder_param_spec) and — when the mesh has a ``seq`` axis — ring
attention for the token dimension, so dp/tp/sp all compose in one step.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.models.transformer import (
    EncoderConfig,
    Params,
    dense_attention,
    embed,
    encoder_param_spec,
    init_encoder_params,
)
from pathway_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, axis_size
from pathway_tpu.parallel.ring_attention import ring_attention_sharded
from pathway_tpu.parallel.sharding import shard_params, tree_specs


class ContrastiveBatch(NamedTuple):
    """(query, positive) token batches; in-batch negatives."""

    q_ids: jax.Array  # [b, t] int32
    q_mask: jax.Array  # [b, t] bool
    d_ids: jax.Array  # [b, t] int32
    d_mask: jax.Array  # [b, t] bool


def _mesh_attn(mesh: Mesh) -> Callable:
    """Attention impl for the mesh: ring over ``seq`` when sharded, else
    dense. Note the ring path reads batch sharded over ``data``."""
    if axis_size(mesh, SEQ_AXIS) > 1:

        def attn(q, k, v, mask):
            # heads stay model-sharded so attention isn't recomputed per
            # model shard (q/k/v arrive with heads split by encoder_param_spec)
            return ring_attention_sharded(
                q, k, v, mesh, k_valid=mask,
                batch_spec=DATA_AXIS, head_spec=MODEL_AXIS,
            )

        return attn
    return dense_attention


def info_nce_loss(
    params: Params,
    batch: ContrastiveBatch,
    cfg: EncoderConfig,
    temperature: float = 0.05,
    attn_fn: Callable = dense_attention,
) -> jax.Array:
    q = embed(params, batch.q_ids, batch.q_mask, cfg, attn_fn)
    d = embed(params, batch.d_ids, batch.d_mask, cfg, attn_fn)
    logits = (q @ d.T) / temperature  # [b, b] — in-batch negatives
    labels = jnp.arange(logits.shape[0])
    l_qd = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    l_dq = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
    return (l_qd.mean() + l_dq.mean()) / 2.0


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jax.Array


def make_train_step(
    cfg: EncoderConfig,
    mesh: Mesh,
    learning_rate: float = 1e-4,
    temperature: float = 0.05,
):
    """Returns ``(init_fn, step_fn, batch_sharding)`` jitted over the mesh.

    ``init_fn(rng) -> TrainState`` places params with encoder_param_spec.
    ``step_fn(state, batch) -> (state, loss)``. ``batch_sharding`` is a
    ContrastiveBatch of NamedShardings — device_put batches with it so they
    arrive data-sharded.
    """
    tx = optax.adamw(learning_rate)
    attn_fn = _mesh_attn(mesh)

    def init_fn(rng: jax.Array) -> TrainState:
        params = init_encoder_params(rng, cfg)
        params = shard_params(mesh, params, encoder_param_spec)
        opt_state = tx.init(params)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    batch_sharding = jax.tree.map(
        lambda _: NamedSharding(mesh, P(DATA_AXIS, None)),
        ContrastiveBatch(None, None, None, None),
        is_leaf=lambda x: x is None,
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def step_fn(state: TrainState, batch: ContrastiveBatch):
        def loss_fn(p):
            return info_nce_loss(p, batch, cfg, temperature, attn_fn)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return init_fn, step_fn, batch_sharding
