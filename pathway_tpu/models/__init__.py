"""Model family: pure-JAX transformers for the LLM xpack's local models.

- transformer.py — BERT-family encoder (MiniLM/BGE configs): embeddings,
  cross-encoder reranking head.
- decoder.py — causal LM (Mistral-style RoPE/GQA/SwiGLU) for local chat.
- train.py — contrastive (InfoNCE) train step over the mesh (dp/tp/sp).

All models are param-pytree + functional-forward with PartitionSpec rules for
tensor parallelism, so the same code runs single-chip and pod-sharded.
"""

from pathway_tpu.models.transformer import (
    EncoderConfig,
    bge_base,
    bge_small,
    cross_encode,
    embed,
    encoder_forward,
    encoder_param_spec,
    init_cross_encoder_params,
    init_encoder_params,
    minilm_l6,
)
from pathway_tpu.models.decoder import (
    DecoderConfig,
    decoder_forward,
    decoder_param_spec,
    greedy_generate,
    sample_generate,
    init_decoder_params,
    mistral_7b,
    tiny_decoder,
)
from pathway_tpu.models.train import (
    ContrastiveBatch,
    TrainState,
    info_nce_loss,
    make_train_step,
)
from pathway_tpu.models.vision import (
    VisionConfig,
    clip_vit_b16,
    init_vision_params,
    normalize_u8,
    preprocess_image,
    preprocess_image_u8,
    vision_forward,
    vision_param_spec,
    vit_tiny,
)

__all__ = [
    "VisionConfig",
    "clip_vit_b16",
    "init_vision_params",
    "normalize_u8",
    "preprocess_image",
    "preprocess_image_u8",
    "vision_forward",
    "vision_param_spec",
    "vit_tiny",
    "ContrastiveBatch",
    "DecoderConfig",
    "EncoderConfig",
    "TrainState",
    "bge_base",
    "bge_small",
    "cross_encode",
    "decoder_forward",
    "decoder_param_spec",
    "embed",
    "encoder_forward",
    "encoder_param_spec",
    "greedy_generate",
    "sample_generate",
    "info_nce_loss",
    "init_cross_encoder_params",
    "init_decoder_params",
    "init_encoder_params",
    "make_train_step",
    "minilm_l6",
    "mistral_7b",
    "tiny_decoder",
]
