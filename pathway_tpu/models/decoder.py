"""Causal decoder LM in pure JAX: RoPE + RMSNorm + SwiGLU + GQA.

The chat path of the LLM xpack. The reference's local chat wraps a HF
``pipeline`` on CPU/GPU torch (reference: python/pathway/xpacks/llm/llms.py:441
HFPipelineChat); here decode is native JAX on TPU: Mistral-style architecture,
static-shape KV cache for generation, and tensor-parallel weight specs over
the ``model`` mesh axis. (Attention is dense; wiring prefill to the ring
kernel in parallel/ring_attention.py is future work.)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pathway_tpu.parallel.mesh import MODEL_AXIS

Params = dict


@dataclasses.dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    layers: int = 32
    heads: int = 32
    kv_heads: int = 8
    intermediate: int = 14336
    max_len: int = 8192
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


def mistral_7b() -> DecoderConfig:
    return DecoderConfig()


def tiny_decoder(vocab_size: int = 512) -> DecoderConfig:
    """Small config for tests/dry runs."""
    return DecoderConfig(
        vocab_size=vocab_size,
        hidden=64,
        layers=2,
        heads=4,
        kv_heads=2,
        intermediate=128,
        max_len=128,
    )


def init_decoder_params(
    rng: jax.Array, cfg: DecoderConfig, dtype: Any = jnp.float32
) -> Params:
    """``dtype=jnp.bfloat16`` stores weights half-size (7B fits a single
    16 GB chip); each tensor is drawn in f32 and cast immediately, so the
    f32 peak is one tensor, not the model."""

    def dense(key, shape):
        scale = 1.0 / math.sqrt(shape[0])
        return (scale * jax.random.normal(key, shape, jnp.float32)).astype(
            dtype
        )

    keys = iter(jax.random.split(rng, 3 + 7 * cfg.layers))
    hd, kvd = cfg.heads * cfg.head_dim, cfg.kv_heads * cfg.head_dim
    p: Params = {
        "tok_emb": (
            0.02
            * jax.random.normal(
                next(keys), (cfg.vocab_size, cfg.hidden), jnp.float32
            )
        ).astype(dtype),
        "final_norm": jnp.ones((cfg.hidden,), jnp.float32),
        "lm_head": dense(next(keys), (cfg.hidden, cfg.vocab_size)),
        "layers": [],
    }
    for _ in range(cfg.layers):
        p["layers"].append(
            {
                "q_w": dense(next(keys), (cfg.hidden, hd)),
                "kv_w": dense(next(keys), (cfg.hidden, 2 * kvd)),
                "o_w": dense(next(keys), (hd, cfg.hidden)),
                "attn_norm": jnp.ones((cfg.hidden,), jnp.float32),
                "gate_w": dense(next(keys), (cfg.hidden, 2 * cfg.intermediate)),
                "down_w": dense(next(keys), (cfg.intermediate, cfg.hidden)),
                "mlp_norm": jnp.ones((cfg.hidden,), jnp.float32),
            }
        )
    return p


def decoder_param_spec(path: tuple, leaf: Any) -> P:
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    if name in ("q_w", "kv_w", "gate_w"):
        return P(None, MODEL_AXIS)
    if name in ("o_w", "down_w"):
        return P(MODEL_AXIS, None)
    if name in ("tok_emb",):
        return P(MODEL_AXIS, None)
    if name in ("lm_head",):
        return P(None, MODEL_AXIS)
    return P()


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    out = x32 * lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (out * scale).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding: x ``[b, t, h, d]``, positions ``[b, t]``."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, t, d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class KVCache(NamedTuple):
    """Static-shape per-layer cache ``[b, max_len, kv_heads, head_dim]``.

    ``valid`` marks usable slots: left-pad positions of shorter prompts in a
    batch stay False forever, so generated tokens never attend to pads.
    """

    k: list
    v: list
    length: jax.Array  # [] int32 — filled prefix
    valid: jax.Array  # [b, max_len] bool — non-pad filled slots


def init_cache(cfg: DecoderConfig, batch: int, max_len: int) -> KVCache:
    shape = (batch, max_len, cfg.kv_heads, cfg.head_dim)
    return KVCache(
        k=[jnp.zeros(shape, cfg.dtype) for _ in range(cfg.layers)],
        v=[jnp.zeros(shape, cfg.dtype) for _ in range(cfg.layers)],
        length=jnp.zeros((), jnp.int32),
        valid=jnp.zeros((batch, max_len), bool),
    )


def _attend(q, k, v, q_pos, k_valid, cfg: DecoderConfig):
    """GQA attention; q ``[b,t,h,d]``, k/v ``[b,s,kvh,d]``; causal by
    absolute position with ``k_valid`` masking unfilled cache slots."""
    g = cfg.heads // cfg.kv_heads
    b, t, h, d = q.shape
    s = k.shape[1]
    qg = q.reshape(b, t, cfg.kv_heads, g, d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32)
    scores = scores / math.sqrt(d)
    k_pos = jnp.arange(s)
    causal = q_pos[:, :, None] >= k_pos[None, None, :]  # [b, t, s]
    mask = causal & k_valid[:, None, :]
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, t, h * d)


def decoder_forward(
    params: Params,
    token_ids: jax.Array,  # [b, t]
    cfg: DecoderConfig,
    cache: KVCache | None = None,
    *,
    attn_mask: jax.Array | None = None,  # [b, t] True = real (non-pad) token
    pos_offset: jax.Array | None = None,  # [b] per-row left-pad count
) -> tuple[jax.Array, KVCache | None]:
    """Logits ``[b, t, vocab]``; appends to ``cache`` when given.

    Without a cache this is plain causal training/scoring forward. With a
    cache, ``token_ids`` is the next chunk (often t=1) starting at
    ``cache.length``. Left-padded batches pass ``attn_mask`` (False on pads,
    which are excluded from attention forever) and ``pos_offset`` (pad count
    per row, subtracted from RoPE positions so token 0 of every prompt sits
    at rotary position 0).
    """
    b, t = token_ids.shape
    x = params["tok_emb"][token_ids].astype(cfg.dtype)
    start = cache.length if cache is not None else jnp.zeros((), jnp.int32)
    # slot index (causal order) vs rotary position (logical, pad-corrected)
    q_slot = start + jnp.arange(t)[None, :].astype(jnp.int32)
    q_slot = jnp.broadcast_to(q_slot, (b, t))
    if pos_offset is not None:
        q_pos = jnp.maximum(q_slot - pos_offset[:, None].astype(jnp.int32), 0)
    else:
        q_pos = q_slot
    new_k, new_v = [], []
    valid_full = None
    if cache is not None:
        chunk_valid = (
            attn_mask if attn_mask is not None else jnp.ones((b, t), bool)
        )
        valid_full = cache.valid.at[:, start + jnp.arange(t)].set(chunk_valid)
    for i, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = (h @ lp["q_w"].astype(cfg.dtype)).reshape(
            b, t, cfg.heads, cfg.head_dim
        )
        kv = h @ lp["kv_w"].astype(cfg.dtype)
        k, v = jnp.split(kv, 2, axis=-1)
        k = k.reshape(b, t, cfg.kv_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.kv_heads, cfg.head_dim)
        q = rope(q, q_pos, cfg.rope_theta)
        k = rope(k, q_pos, cfg.rope_theta)
        if cache is not None:
            # scatter the chunk at positions [start, start+t)
            idx = start + jnp.arange(t)
            k_full = cache.k[i].at[:, idx].set(k)
            v_full = cache.v[i].at[:, idx].set(v)
            new_k.append(k_full)
            new_v.append(v_full)
            a = _attend(q, k_full, v_full, q_slot, valid_full, cfg)
        else:
            k_valid = (
                attn_mask
                if attn_mask is not None
                else jnp.ones((b, t), bool)
            )
            a = _attend(q, k, v, q_slot, k_valid, cfg)
        x = x + (a @ lp["o_w"].astype(cfg.dtype))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate_up = h @ lp["gate_w"].astype(cfg.dtype)
        gate, up = jnp.split(gate_up, 2, axis=-1)
        x = x + (jax.nn.silu(gate) * up) @ lp["down_w"].astype(cfg.dtype)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    if cache is not None:
        cache = KVCache(k=new_k, v=new_v, length=start + t, valid=valid_full)
    return logits, cache


def _generate_loop(
    params: Params,
    prompt_ids: jax.Array,
    cfg: DecoderConfig,
    max_new_tokens: int,
    eos_id: int | None,
    prompt_mask: jax.Array | None,
    choose,
) -> jax.Array:
    """Shared decode scaffold: prompt prefill, per-step cache decode,
    EOS padding. ``choose(logits [b, vocab], step_no) -> [b] int32`` picks
    each next token (argmax for greedy, filtered categorical for
    sampling).

    ``prompt_mask`` handles left-padded batches of unequal-length prompts:
    pad slots are never attended to and RoPE positions are shifted so every
    prompt starts at rotary position 0 (ADVICE r1). Tokens after EOS are
    padded with ``eos_id``.
    """
    b, t_prompt = prompt_ids.shape
    max_len = t_prompt + max_new_tokens
    cache = init_cache(cfg, b, max_len)
    if prompt_mask is not None:
        # left-padding: pad count = leading False run = t_prompt - true count
        pos_offset = t_prompt - prompt_mask.sum(axis=1).astype(jnp.int32)
    else:
        pos_offset = jnp.zeros((b,), jnp.int32)
    logits, cache = decoder_forward(
        params,
        prompt_ids,
        cfg,
        cache,
        attn_mask=prompt_mask,
        pos_offset=pos_offset,
    )
    next_tok = choose(logits[:, -1], 0)
    done = jnp.zeros((b,), bool)

    def step(carry, step_no):
        cache, tok, done = carry
        logits, cache = decoder_forward(
            params, tok[:, None], cfg, cache, pos_offset=pos_offset
        )
        new_tok = choose(logits[:, -1], step_no + 1)
        if eos_id is not None:
            done = done | (tok == eos_id)
            new_tok = jnp.where(done, eos_id, new_tok)
        return (cache, new_tok, done), tok

    (_, _, _), toks = lax.scan(
        step, (cache, next_tok, done), jnp.arange(max_new_tokens)
    )
    return toks.transpose(1, 0)  # [b, max_new]


def greedy_generate(
    params: Params,
    prompt_ids: jax.Array,  # [b, t_prompt]
    cfg: DecoderConfig,
    max_new_tokens: int,
    eos_id: int | None = None,
    prompt_mask: jax.Array | None = None,  # [b, t_prompt] True = real token
) -> jax.Array:
    """Greedy decode with a static-shape cache; returns ``[b, max_new]``."""

    def choose(logits: jax.Array, _step: Any) -> jax.Array:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return _generate_loop(
        params, prompt_ids, cfg, max_new_tokens, eos_id, prompt_mask, choose
    )


def _filter_logits(
    logits: jax.Array, top_k: int | None, top_p: float | None
) -> jax.Array:
    """HF-style logit filtering: keep the top-k logits and/or the nucleus
    whose cumulative probability reaches top_p; everything else -> -inf."""
    if top_k is not None and 0 < top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        order = jnp.argsort(-logits, axis=-1)
        sorted_desc = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # keep tokens up to and including the one crossing top_p; the
        # exclusive-cumulative test against a positive threshold always
        # keeps the argmax (HF's min_tokens_to_keep=1) — clamp guards
        # top_p<=0, which would otherwise mask EVERY logit to -inf.
        # Keep flags map back through the inverse permutation (index-based
        # like HF, so boundary-logit TIES outside the nucleus are dropped
        # rather than kept by a value threshold).
        keep_sorted = (cumulative - probs) < max(top_p, 1e-9)
        inverse = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inverse, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def sample_generate(
    params: Params,
    prompt_ids: jax.Array,  # [b, t_prompt]
    cfg: DecoderConfig,
    max_new_tokens: int,
    row_seeds: jax.Array,  # [b] uint32 — per-row PRNG seeds
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    prompt_mask: jax.Array | None = None,
) -> jax.Array:
    """Sampling decode (reference HFPipelineChat forwards do_sample/
    temperature/top_k/top_p to HF generate, llms.py:441): temperature
    scaling then top-k/top-p filtering then categorical sampling, with a
    per-ROW PRNG key folded per step — so each row's generation is a
    deterministic function of (params, its prompt, its seed), independent
    of how rows are batched (the engine's retraction consistency needs
    deterministic UDF outputs)."""
    keys = jax.vmap(jax.random.key)(row_seeds)
    inv_temp = 1.0 / max(temperature, 1e-6)

    def choose(logits: jax.Array, step_no: Any) -> jax.Array:
        step_keys = jax.vmap(jax.random.fold_in, (0, None))(keys, step_no)
        filtered = _filter_logits(logits * inv_temp, top_k, top_p)
        return jax.vmap(jax.random.categorical)(step_keys, filtered).astype(
            jnp.int32
        )

    return _generate_loop(
        params, prompt_ids, cfg, max_new_tokens, eos_id, prompt_mask, choose
    )
