"""BERT-family text encoder in pure JAX (pytree params, functional forward).

Model math for the LLM xpack's *local* models. The reference delegates local
embedding/reranking to CPU/GPU torch via sentence-transformers
(reference: python/pathway/xpacks/llm/embedders.py:270, rerankers.py:186);
here the models are native JAX so they jit onto the MXU, batch with the UDF
microbatcher, and shard over the mesh (tensor parallel via PartitionSpecs,
sequence parallel via ring attention).

Configs mirror the architectures the reference's defaults load:
``minilm_l6`` (all-MiniLM-L6-v2) and ``bge_base`` (BGE-base-en / BERT-base).
Weights are randomly initialised (benchmarks measure architecture
throughput); the param tree uses HF BERT naming-compatible structure so a
checkpoint importer can be added without changing the forward pass.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pathway_tpu.parallel.mesh import MODEL_AXIS

Params = dict  # nested dict pytree of jnp arrays


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden: int = 384
    layers: int = 6
    heads: int = 12
    intermediate: int = 1536
    max_len: int = 512
    type_vocab: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16  # compute dtype; params stay float32
    pooling: str = "mean"  # mean | cls

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


def minilm_l6() -> EncoderConfig:
    return EncoderConfig(hidden=384, layers=6, heads=12, intermediate=1536)


def bge_base() -> EncoderConfig:
    return EncoderConfig(
        hidden=768, layers=12, heads=12, intermediate=3072, pooling="cls"
    )


def bge_small() -> EncoderConfig:
    return EncoderConfig(
        hidden=384, layers=12, heads=12, intermediate=1536, pooling="cls"
    )


# -- init ---------------------------------------------------------------------


def _dense_init(rng, shape, scale=0.02):
    return scale * jax.random.normal(rng, shape, jnp.float32)


def init_encoder_params(rng: jax.Array, cfg: EncoderConfig) -> Params:
    keys = iter(jax.random.split(rng, 6 + 8 * cfg.layers))
    p: Params = {
        "tok_emb": _dense_init(next(keys), (cfg.vocab_size, cfg.hidden)),
        "pos_emb": _dense_init(next(keys), (cfg.max_len, cfg.hidden)),
        "type_emb": _dense_init(next(keys), (cfg.type_vocab, cfg.hidden)),
        "emb_ln": _ln_init(cfg.hidden),
        "layers": [],
    }
    for _ in range(cfg.layers):
        p["layers"].append(
            {
                "qkv_w": _dense_init(next(keys), (cfg.hidden, 3 * cfg.hidden)),
                "qkv_b": jnp.zeros((3 * cfg.hidden,), jnp.float32),
                "out_w": _dense_init(next(keys), (cfg.hidden, cfg.hidden)),
                "out_b": jnp.zeros((cfg.hidden,), jnp.float32),
                "attn_ln": _ln_init(cfg.hidden),
                "fc1_w": _dense_init(next(keys), (cfg.hidden, cfg.intermediate)),
                "fc1_b": jnp.zeros((cfg.intermediate,), jnp.float32),
                "fc2_w": _dense_init(next(keys), (cfg.intermediate, cfg.hidden)),
                "fc2_b": jnp.zeros((cfg.hidden,), jnp.float32),
                "mlp_ln": _ln_init(cfg.hidden),
            }
        )
    return p


def _ln_init(dim: int) -> Params:
    return {
        "scale": jnp.ones((dim,), jnp.float32),
        "bias": jnp.zeros((dim,), jnp.float32),
    }


# -- partition specs (tensor parallelism) -------------------------------------


def encoder_param_spec(path: tuple, leaf: Any) -> P:
    """PartitionSpec per parameter: attention/MLP matrices split over the
    ``model`` axis (Megatron-style column/row split); embeddings split over
    the vocab/position dim; everything 1-D replicated."""
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    if name in ("qkv_w", "fc1_w"):
        return P(None, MODEL_AXIS)
    if name in ("out_w", "fc2_w"):
        return P(MODEL_AXIS, None)
    if name in ("tok_emb", "pos_emb", "type_emb"):
        return P(MODEL_AXIS, None)
    return P()


# -- forward ------------------------------------------------------------------


def layer_norm(x: jax.Array, p: Params, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (out * p["scale"] + p["bias"]).astype(x.dtype)


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, mask: jax.Array | None
) -> jax.Array:
    """Plain masked attention: q/k/v ``[b, t, h, d]``, mask ``[b, t]``."""
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) / math.sqrt(d)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", p, v)


AttnFn = Callable[[jax.Array, jax.Array, jax.Array, jax.Array | None], jax.Array]


def default_attn_fn() -> AttnFn:
    """The attention implementation for the current backend: the Pallas
    flash kernel on real TPU backends (O(t·d) HBM traffic), dense
    attention elsewhere (CPU tests, virtual meshes — where the kernel
    would run interpreted and slower). ``PATHWAY_DISABLE_FLASH_ATTENTION=1``
    forces dense everywhere."""
    import os

    if os.environ.get("PATHWAY_DISABLE_FLASH_ATTENTION") == "1":
        return dense_attention
    if jax.default_backend() in ("tpu", "axon"):
        from pathway_tpu.ops.flash_attention import flash_attention

        return flash_attention
    return dense_attention


def encoder_forward(
    params: Params,
    token_ids: jax.Array,  # [b, t] int32
    mask: jax.Array | None,  # [b, t] bool (True = real token)
    cfg: EncoderConfig,
    attn_fn: AttnFn | None = None,
) -> jax.Array:
    """Token-level hidden states ``[b, t, hidden]`` (compute in cfg.dtype)."""
    if attn_fn is None:
        attn_fn = default_attn_fn()
    b, t = token_ids.shape
    x = (
        params["tok_emb"][token_ids]
        + params["pos_emb"][None, :t]
        + params["type_emb"][0][None, None]
    ).astype(cfg.dtype)
    x = layer_norm(x, params["emb_ln"], cfg.layer_norm_eps)
    for lp in params["layers"]:
        qkv = x @ lp["qkv_w"].astype(cfg.dtype) + lp["qkv_b"].astype(cfg.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, cfg.heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.heads, cfg.head_dim)
        a = attn_fn(q, k, v, mask).reshape(b, t, cfg.hidden)
        a = a @ lp["out_w"].astype(cfg.dtype) + lp["out_b"].astype(cfg.dtype)
        x = layer_norm(x + a, lp["attn_ln"], cfg.layer_norm_eps)
        h = x @ lp["fc1_w"].astype(cfg.dtype) + lp["fc1_b"].astype(cfg.dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = h @ lp["fc2_w"].astype(cfg.dtype) + lp["fc2_b"].astype(cfg.dtype)
        x = layer_norm(x + h, lp["mlp_ln"], cfg.layer_norm_eps)
    return x


def pool(
    hidden: jax.Array, mask: jax.Array | None, cfg: EncoderConfig
) -> jax.Array:
    """Sentence embedding from token states, L2-normalised ``[b, hidden]``."""
    h32 = hidden.astype(jnp.float32)
    if cfg.pooling == "cls":
        emb = h32[:, 0]
    else:
        if mask is None:
            emb = h32.mean(axis=1)
        else:
            m = mask.astype(jnp.float32)[..., None]
            emb = (h32 * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1e-9)
    return emb / jnp.maximum(
        jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-12
    )


def embed(
    params: Params,
    token_ids: jax.Array,
    mask: jax.Array | None,
    cfg: EncoderConfig,
    attn_fn: AttnFn | None = None,
) -> jax.Array:
    """The embedder entry point: tokens -> normalised sentence embeddings.
    ``attn_fn=None`` picks the backend default (flash on TPU)."""
    return pool(encoder_forward(params, token_ids, mask, cfg, attn_fn), mask, cfg)


# -- cross-encoder (reranker) -------------------------------------------------


def init_cross_encoder_params(rng: jax.Array, cfg: EncoderConfig) -> Params:
    k1, k2 = jax.random.split(rng)
    p = init_encoder_params(k1, cfg)
    p["head_w"] = _dense_init(k2, (cfg.hidden, 1))
    p["head_b"] = jnp.zeros((1,), jnp.float32)
    return p


def cross_encode(
    params: Params,
    token_ids: jax.Array,  # [b, t] — query [SEP] doc pairs
    mask: jax.Array | None,
    cfg: EncoderConfig,
) -> jax.Array:
    """Relevance score per pair ``[b]`` (pre-sigmoid logit)."""
    hidden = encoder_forward(params, token_ids, mask, cfg)
    cls = hidden[:, 0].astype(jnp.float32)
    return (cls @ params["head_w"] + params["head_b"])[:, 0]
