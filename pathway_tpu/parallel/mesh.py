"""Device mesh construction and the process-global mesh registry.

The reference sizes its worker pool from env (PATHWAY_THREADS × PATHWAY_PROCESSES,
reference: src/engine/dataflow/config.rs:88-120). Here the analogous resource is
the TPU device mesh: ``make_mesh`` factors the available devices over the named
axes (data, model, seq, expert) and the rest of the framework picks shardings
against those names. A process-global current mesh plays the role the timely
worker config plays in the reference — one fabric per run, consulted by every
device-touching operator.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"

_AXIS_ORDER = (DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Requested axis sizes; ``None`` means absorb the remaining devices."""

    data: int | None = None
    model: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        fixed = self.model * self.seq * self.expert
        if n_devices % fixed != 0:
            raise ValueError(
                f"cannot factor {n_devices} devices over model={self.model} "
                f"seq={self.seq} expert={self.expert}"
            )
        data = self.data if self.data is not None else n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh {data}x{self.expert}x{self.seq}x{self.model} = {total} "
                f"!= {n_devices} devices"
            )
        return {
            DATA_AXIS: data,
            EXPERT_AXIS: self.expert,
            SEQ_AXIS: self.seq,
            MODEL_AXIS: self.model,
        }


def make_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all) with the standard axes.

    Axes of size 1 are still present in the mesh so shardings written against
    the full axis vocabulary work unchanged on any topology — a 1-chip dev run
    and a v5e-256 pod use the same PartitionSpecs.
    """
    if config is None:
        config = MeshConfig(**axis_sizes)
    elif axis_sizes:
        raise TypeError("pass either a MeshConfig or axis sizes, not both")
    if devices is None:
        devices = jax.devices()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in _AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, _AXIS_ORDER)


_current_mesh: Mesh | None = None


def set_mesh(mesh: Mesh | None) -> None:
    global _current_mesh
    _current_mesh = mesh


def current_mesh() -> Mesh | None:
    return _current_mesh


def get_mesh() -> Mesh:
    """The mesh in effect, creating a default all-data-parallel one lazily."""
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = make_mesh()
    return _current_mesh


def axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape[axis]) if axis in mesh.shape else 1


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple) * multiple) if multiple > 1 else n
