"""Ring attention: exact attention over sequence-sharded inputs.

Long-context path of the framework. Sequences are split over the ``seq`` mesh
axis; each device holds a local block of Q/K/V and K/V blocks rotate around
the ring via `lax.ppermute` while a flash-style online softmax accumulates the
output — so memory stays O(T/n) per device and the collective rides ICI.
(The reference has no model math at all — SURVEY.md §5.7; this is new
TPU-first design, following the blockwise-attention recipe from the public
ring-attention literature, see PAPERS.md.)

`ring_attention` is the inside-shard_map kernel; `ring_attention_sharded`
wraps it in shard_map over a mesh for direct use.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pathway_tpu.parallel.mesh import SEQ_AXIS, axis_size as mesh_axis_size


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = False,
    scale: float | None = None,
    bias: jax.Array | None = None,
    k_valid: jax.Array | None = None,
) -> jax.Array:
    """Attention over a sequence-sharded ring. Call inside shard_map.

    Args:
      q, k, v: local blocks ``[batch, t_local, heads, head_dim]``.
      axis_name: mesh axis the sequence is sharded over.
      axis_size: static size of that axis (devices in the ring).
      causal: apply a causal mask using *global* positions.
      bias: optional local additive bias ``[batch, heads, t_local, t_local]``
        applied only to the diagonal (self) block — used for local masks.
      k_valid: optional key padding mask ``[batch, s_local]`` (True = attend);
        rotates around the ring together with its K/V block.

    Returns the local output block ``[batch, t_local, heads, head_dim]``.
    """
    b, t_loc, h, d = q.shape
    s_loc = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    my_idx = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32) * scale
    o = jnp.zeros((b, t_loc, h, d), jnp.float32)
    m = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)

    q_pos = my_idx * t_loc + jnp.arange(t_loc)

    def accumulate(o, m, l, k_blk, v_blk, valid_blk, step):
        # K/V block currently held arrived from device (my_idx - step) mod n.
        src = (my_idx - step) % axis_size
        s = jnp.einsum("bthd,bshd->bhts", q32, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        if valid_blk is not None:
            s = jnp.where(valid_blk[:, None, None, :], s, -jnp.inf)
        if bias is not None:
            s = jnp.where(step == 0, s + bias, s)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # Rows with no valid key yet keep m == -inf; exp(-inf - -inf) guards.
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        l = l * corr + p.sum(axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhts,bshd->bthd", p, v_blk.astype(jnp.float32)
        )
        return o, m_new, l

    def block(carry, step):
        o, m, l, k_blk, v_blk, valid_blk = carry
        # Rotate first (steps 1..n-1) so the last block needs no ppermute.
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        if valid_blk is not None:
            valid_blk = lax.ppermute(valid_blk, axis_name, perm)
        o, m, l = accumulate(o, m, l, k_blk, v_blk, valid_blk, step)
        return (o, m, l, k_blk, v_blk, valid_blk), None

    o, m, l = accumulate(o, m, l, k, v, k_valid, 0)
    if axis_size > 1:
        (o, m, l, _, _, _), _ = lax.scan(
            block, (o, m, l, k, v, k_valid), jnp.arange(1, axis_size)
        )
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: float | None = None,
    k_valid: jax.Array | None = None,
    seq_axis: str = SEQ_AXIS,
    batch_spec: Any = None,
    head_spec: Any = None,
) -> jax.Array:
    """shard_map wrapper: global ``[B, T, H, D]`` in, same out.

    T is sharded over ``seq_axis``; batch/heads may additionally be sharded
    via ``batch_spec`` / ``head_spec`` (e.g. "data" / "model").
    ``k_valid`` is a global ``[B, T]`` key padding mask.
    """
    n = mesh_axis_size(mesh, seq_axis)
    t_spec = seq_axis if n > 1 else None
    spec = P(batch_spec, t_spec, head_spec, None)
    mask_spec = P(batch_spec, t_spec)

    def fn(q, k, v, valid):
        return ring_attention(
            q, k, v,
            axis_name=seq_axis,
            axis_size=n,
            causal=causal,
            scale=scale,
            k_valid=valid,
        )

    from pathway_tpu.parallel.sharding import shard_map_norep

    return shard_map_norep(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, None if k_valid is None else mask_spec),
        out_specs=spec,
    )(q, k, v, k_valid)
