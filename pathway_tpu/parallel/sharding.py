"""Sharding helpers: NamedShardings from the standard axis vocabulary.

Instead of the reference's per-record hash exchange (Key::shard, reference:
src/engine/value.rs:94-130), device state is laid out once with
`jax.sharding.NamedSharding` and XLA inserts the collectives. These helpers
keep PartitionSpec construction in one place so models, indexes and UDF
microbatches agree on axis names.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel.mesh import DATA_AXIS


def named_sharding(mesh: Mesh, *spec: Any) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def shard_map_norep(
    fn: Callable, *, mesh: Mesh, in_specs: Any, out_specs: Any
) -> Callable:
    """``shard_map`` with replication checking off, on any jax this repo
    meets: >= 0.5 exposes it at top level (``check_vma``), older builds
    only under ``jax.experimental.shard_map`` (``check_rep``). The kernels
    here all reduce across an axis inside the mapped function, which the
    checker cannot see through — hence always off."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree: Any, axis: str = DATA_AXIS) -> Any:
    """Put a host batch on device, sharded along dim 0 over ``axis``.

    Leading dims not divisible by the axis size are the caller's problem —
    microbatch padding (pathway_tpu/internals/udfs) guarantees divisibility
    before anything reaches the device.
    """
    sharding = named_sharding(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def shard_params(
    mesh: Mesh,
    params: Any,
    spec_fn: Callable[[tuple, Any], P],
) -> Any:
    """Place a parameter pytree using ``spec_fn(path, leaf) -> PartitionSpec``."""

    def place(path: tuple, leaf: Any) -> Any:
        spec = spec_fn(path, leaf)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def tree_specs(params: Any, spec_fn: Callable[[tuple, Any], P]) -> Any:
    """A pytree of PartitionSpecs matching ``params`` (for jit in/out shardings)."""
    return jax.tree_util.tree_map_with_path(spec_fn, params)
