"""Ulysses-style sequence parallelism: all-to-all head scatter.

The second long-context strategy next to ring attention
(parallel/ring_attention.py): instead of rotating K/V blocks around a
ring, every device exchanges its sequence shard for a HEAD shard with one
``all_to_all`` before attention and swaps back after — each device then
runs ordinary full-sequence attention over ``heads / n`` heads. (The
reference has no model math at all, SURVEY.md §5.7; this is new TPU-first
design after the public DeepSpeed-Ulysses recipe, see PAPERS.md.)

Trade-offs vs the ring (why the framework ships both):
- Ulysses moves Q, K and V once each (two all-to-alls total) and computes
  attention in one fused [T, T] matmul per head group — fewer, larger MXU
  ops, better for moderate sequence lengths where O(T^2 / n) score memory
  still fits.
- Ring keeps score memory at O((T/n)^2) per step and overlaps K/V
  transfer with compute — better for extreme sequence lengths.
- Ulysses requires ``heads % n == 0``; the ring has no head constraint.

``ulysses_attention`` is the inside-shard_map kernel; the sharded wrapper
mirrors ``ring_attention_sharded`` so callers can switch strategies with
one name change.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pathway_tpu.parallel.mesh import SEQ_AXIS, axis_size as mesh_axis_size


def _attend(q, k, v, *, causal, scale, k_valid):
    """Plain full-sequence attention: [b, t, h, d] x [b, s, h, d].

    Scores and softmax run in float32 regardless of input dtype (the ring
    kernel upcasts the same way); fully-masked query rows output exactly 0,
    matching ring_attention's online-softmax behavior."""
    out_dtype = q.dtype
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scores = jnp.einsum("bthd,bshd->bhts", qf, kf) * scale
    if k_valid is not None:
        scores = jnp.where(k_valid[:, None, None, :], scores, -jnp.inf)
    if causal:
        t = q.shape[1]
        s = k.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(s)[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    # rows with every position masked (padding queries) would softmax NaN;
    # compute them on neutral scores, then zero their OUTPUT (never let
    # them attend uniformly — that would leak masked/future values)
    all_masked = jnp.all(jnp.isneginf(scores), axis=-1, keepdims=True)
    probs = jax.nn.softmax(jnp.where(all_masked, 0.0, scores), axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, vf)
    out = jnp.where(
        jnp.swapaxes(all_masked, 1, 2)[..., 0, None], 0.0, out
    )
    return out.astype(out_dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = False,
    scale: float | None = None,
    k_valid: jax.Array | None = None,
) -> jax.Array:
    """Attention over sequence-sharded inputs via head all-to-all.

    Call inside shard_map. Local blocks are ``[batch, t_local, heads,
    head_dim]`` with ``heads % axis_size == 0``; ``k_valid`` is the local
    key padding mask ``[batch, t_local]`` (True = attend). Returns the
    local output block ``[batch, t_local, heads, head_dim]``.
    """
    b, t_local, heads, head_dim = q.shape
    if heads % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by the sequence "
            f"axis size ({axis_size}); use ring_attention otherwise"
        )
    if scale is None:
        scale = head_dim**-0.5

    def seq_to_heads(x):
        # [b, t_local, heads, d] -> [b, t_local*n ( = T global), heads/n, d]
        # all_to_all: scatter the head axis, gather the sequence axis
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg = seq_to_heads(q)
    kg = seq_to_heads(k)
    vg = seq_to_heads(v)
    valid_g = None
    if k_valid is not None:
        # the key mask follows K's sequence gather: [b, t_local] -> [b, T]
        valid_g = lax.all_gather(k_valid, axis_name, axis=1, tiled=True)
    out_g = _attend(qg, kg, vg, causal=causal, scale=scale, k_valid=valid_g)
    return heads_to_seq(out_g)


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: float | None = None,
    k_valid: jax.Array | None = None,
    seq_axis: str = SEQ_AXIS,
    batch_spec: Any = None,
    head_spec: Any = None,
) -> jax.Array:
    """shard_map wrapper: global ``[B, T, H, D]`` in, same out — the exact
    signature of ``ring_attention_sharded``, so callers switch strategies
    with one name change. T is sharded over ``seq_axis``; batch/heads may
    additionally be sharded via ``batch_spec`` / ``head_spec``."""
    n = mesh_axis_size(mesh, seq_axis)
    t_spec = seq_axis if n > 1 else None
    spec = P(batch_spec, t_spec, head_spec, None)
    mask_spec = P(batch_spec, t_spec)

    def fn(q, k, v, valid):
        return ulysses_attention(
            q, k, v,
            axis_name=seq_axis,
            axis_size=n,
            causal=causal,
            scale=scale,
            k_valid=valid,
        )

    from pathway_tpu.parallel.sharding import shard_map_norep

    return shard_map_norep(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, None if k_valid is None else mask_spec),
        out_specs=spec,
    )(q, k, v, k_valid)
