"""Parallelism & distribution layer (TPU-native).

The reference scales via timely dataflow workers exchanging records over TCP
(reference: src/engine/dataflow/config.rs:63-120, SURVEY.md §2.9/§2.10). The
TPU-native design replaces that substrate with a `jax.sharding.Mesh` over the
ICI/DCN fabric: device-resident state (vector indexes, model params,
microbatched UDF compute) is sharded with `NamedSharding`s and exchanged via
XLA collectives (all_gather / psum / ppermute / reduce_scatter) instead of
TCP exchange channels. The host-side commit scheduler stays the control
plane; everything that touches numbers rides the mesh.

Axes (fixed vocabulary, used by shardings throughout the framework):
- ``data``  — data parallelism: rows/keys/documents are hash-partitioned
  across this axis, the TPU analog of the reference's worker key-sharding
  (src/engine/value.rs:94-130).
- ``model`` — tensor parallelism for model weights (attention heads / mlp
  columns) and the vector-index feature dimension.
- ``seq``   — sequence/context parallelism: long sequences are split across
  devices and attention runs as ring attention (ppermute over this axis).
- ``expert`` — expert parallelism for MoE blocks.
"""

from pathway_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    MeshConfig,
    current_mesh,
    get_mesh,
    make_mesh,
    set_mesh,
)
from pathway_tpu.parallel.sharding import (
    named_sharding,
    replicated,
    shard_batch,
    shard_params,
    tree_specs,
)
from pathway_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_sharded,
)
from pathway_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_sharded,
)

__all__ = [
    "DATA_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "MeshConfig",
    "current_mesh",
    "get_mesh",
    "make_mesh",
    "named_sharding",
    "replicated",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "set_mesh",
    "shard_batch",
    "shard_params",
    "tree_specs",
]
