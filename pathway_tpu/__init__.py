"""pathway_tpu — a TPU-native unified batch/streaming dataflow framework.

Brand-new implementation with the capabilities of the reference Pathway
framework (see SURVEY.md): a declarative Python API building an incremental
dataflow graph — tables as keyed update streams (key, row, time, diff) —
executed by a host-side commit scheduler with the compute path (embedders,
rerankers, vector search, decode) on TPU via JAX/XLA/Pallas.
"""

from pathway_tpu.internals import lockwatch as _lockwatch

# PATHWAY_TPU_LOCKWATCH=1: wrap Lock/RLock creation BEFORE the runtime
# modules below instantiate theirs, so the order recorder sees them all
_lockwatch.maybe_install()

from pathway_tpu.engine.value import (  # noqa: E402
    ERROR,
    DateTimeNaive,
    DateTimeUtc,
    Duration,
    Json,
    Pointer,
    PyObjectWrapper,
    unsafe_make_pointer,
)
from pathway_tpu.internals import dtype as _dt
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply,
    apply_async,
    apply_with_type,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    make_tuple,
    require,
    unwrap,
)
from pathway_tpu.internals.parse_graph import G, run, run_all
from pathway_tpu.internals.schema import (
    Schema,
    column_definition,
    schema_builder,
    assert_table_has_schema,
    schema_from_csv,
    schema_from_dict,
    schema_from_types,
)
from pathway_tpu.internals.table import JoinMode, Table
from pathway_tpu.internals.thisclass import left, right, this
from pathway_tpu.internals import universe as _universe_mod

from pathway_tpu import debug  # noqa: E402  (imports Table)
from pathway_tpu import demo  # noqa: E402
from pathway_tpu import io  # noqa: E402
from pathway_tpu import persistence  # noqa: E402
from pathway_tpu import stdlib  # noqa: E402
from pathway_tpu.internals.config import PathwayConfig, get_pathway_config, set_license_key  # noqa: E402
from pathway_tpu.internals.errors import global_error_log, local_error_log  # noqa: E402
from pathway_tpu.internals.export_import import export_table, import_table  # noqa: E402
from pathway_tpu.internals.row_transformer import (  # noqa: E402
    ClassArg,
    attribute,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)
from pathway_tpu.internals.monitoring import MonitoringLevel  # noqa: E402
from pathway_tpu.internals.telemetry import set_monitoring_config  # noqa: E402
from pathway_tpu.stdlib import temporal  # noqa: E402


def load_yaml(stream):
    """Declarative app templates (reference yaml_loader.py:214). Imported
    lazily so pyyaml stays an optional dependency."""
    from pathway_tpu.internals.yaml_loader import load_yaml as _load_yaml

    return _load_yaml(stream)
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer  # noqa: E402
from pathway_tpu.internals import udfs  # noqa: E402
from pathway_tpu.internals.iterate import iterate  # noqa: E402
from pathway_tpu.internals.sql import sql  # noqa: E402
from pathway_tpu.internals.interactive import (  # noqa: E402
    LiveTable,
    enable_interactive_mode,
    stop_interactive_mode,
)
from pathway_tpu.internals.udfs import UDF, udf  # noqa: E402


class universes:
    """Universe promises (reference: pw.universes.*)."""

    @staticmethod
    def promise_are_equal(*tables: Table) -> None:
        for other in tables[1:]:
            _universe_mod.solver.register_equal(
                tables[0]._universe, other._universe
            )

    @staticmethod
    def promise_is_subset_of(sub: Table, sup: Table) -> None:
        _universe_mod.solver.register_subset(sub._universe, sup._universe)


def wrap_py_object(obj: object, **kwargs: object) -> PyObjectWrapper:
    return PyObjectWrapper(obj)


__version__ = "0.1.0"

__all__ = [
    "LiveTable",
    "enable_interactive_mode",
    "stop_interactive_mode",
    "ERROR",
    "ColumnExpression",
    "ColumnReference",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "G",
    "JoinMode",
    "Json",
    "Pointer",
    "PyObjectWrapper",
    "Schema",
    "Table",
    "apply",
    "apply_async",
    "apply_with_type",
    "cast",
    "coalesce",
    "column_definition",
    "debug",
    "declare_type",
    "fill_error",
    "if_else",
    "iterate",
    "left",
    "make_tuple",
    "persistence",
    "reducers",
    "require",
    "right",
    "run",
    "run_all",
    "schema_builder",
    "schema_from_dict",
    "schema_from_types",
    "sql",
    "stdlib",
    "temporal",
    "MonitoringLevel",
    "PathwayConfig",
    "get_pathway_config",
    "set_license_key",
    "load_yaml",
    "export_table",
    "global_error_log",
    "local_error_log",
    "schema_from_csv",
    "assert_table_has_schema",
    "import_table",
    "ClassArg",
    "attribute",
    "input_attribute",
    "input_method",
    "method",
    "output_attribute",
    "transformer",
    "set_monitoring_config",
    "AsyncTransformer",
    "this",
    "udf",
    "UDF",
    "udfs",
    "universes",
    "unsafe_make_pointer",
    "unwrap",
    "wrap_py_object",
]
