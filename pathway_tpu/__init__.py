"""pathway_tpu — a TPU-native unified batch/streaming dataflow framework.

Brand-new implementation with the capabilities of the reference Pathway
framework (see SURVEY.md): a declarative Python API building an incremental
dataflow graph — tables as keyed update streams (key, row, time, diff) —
executed by a host-side commit scheduler with the compute path (embedders,
rerankers, vector search, decode) on TPU via JAX/XLA/Pallas.
"""

from pathway_tpu.engine.value import (
    ERROR,
    DateTimeNaive,
    DateTimeUtc,
    Duration,
    Json,
    Pointer,
    PyObjectWrapper,
)
from pathway_tpu.internals import dtype as _dt
from pathway_tpu.internals.schema import (
    Schema,
    column_definition,
    schema_builder,
    schema_from_dict,
    schema_from_types,
)

__version__ = "0.1.0"

__all__ = [
    "ERROR",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "Json",
    "Pointer",
    "PyObjectWrapper",
    "Schema",
    "column_definition",
    "schema_builder",
    "schema_from_dict",
    "schema_from_types",
]
