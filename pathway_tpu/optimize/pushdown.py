"""Projection pushdown — narrow producers to the columns consumers read.

One backward sweep over the primary scope (highest index first, so a
narrowed downstream Expression shrinks the reference set its own producer
sees in the same pass).  A producer is narrowed when

- it is an exact-type :class:`StaticSource` or :class:`ExpressionNode`
  inside the shared region, with at least one consumer;
- it is not observed (``_pw_observed`` capture targets) or protected
  (cross-process / sink-region consumers), so the full consumer set is
  known and nobody reads its state directly;
- *every* consumer is a kind whose column references can be remapped in
  place: Expression (ColumnRef rewrite on a private expression copy),
  BatchApply (``arg_cols``), Ix port 0 (``key_col``).

The pass only runs on graphs with sinks (SubscribeNode present, or
cross-process sink consumers): in sink-less engine graphs terminal *and*
intermediate state is routinely observed directly (bench/tests), and a
narrowed row tuple would be visible there.

Narrowing is decided once on the primary scope and replayed on every
replica scope by node index, keeping the sharded replicas bit-identical.
Consumer expression trees are copied before the ColumnRef rewrite —
compilers may share subtrees across nodes, and leaf *values* are shared
(never deep-copied) so evaluated outputs stay identical objects.
"""

from __future__ import annotations

from pathway_tpu.analysis.usage import expr_refs
from pathway_tpu.engine import expression as ex
from pathway_tpu.engine import graph as g


def _consumer_refs(consumer: g.Node, port: int) -> set[int] | None:
    """Producer columns ``consumer`` reads through ``port``; None when the
    consumer kind cannot be remapped (which vetoes narrowing)."""
    if type(consumer) is g.ExpressionNode and port == 0:
        refs: set[int] = set()
        for e in consumer.expressions:
            expr_refs(e, refs)
        return refs
    if type(consumer) is g.BatchApplyNode and port == 0:
        return set(consumer.arg_cols)
    if type(consumer) is g.IxNode and port == 0:
        return {consumer.key_col}
    return None


def _copy_expr(expr: ex.EngineExpression, memo: dict) -> ex.EngineExpression:
    """Copy an expression tree, sharing leaf values and preserving interior
    sharing (memo) — only EngineExpression nodes are duplicated."""
    got = memo.get(id(expr))
    if got is not None:
        return got
    cls = type(expr)
    new = cls.__new__(cls)
    memo[id(expr)] = new
    for klass in cls.__mro__:
        for slot in getattr(klass, "__slots__", ()):
            try:
                v = getattr(expr, slot)
            except AttributeError:
                continue
            if isinstance(v, ex.EngineExpression):
                v = _copy_expr(v, memo)
            elif isinstance(v, list):
                v = [
                    _copy_expr(i, memo)
                    if isinstance(i, ex.EngineExpression)
                    else i
                    for i in v
                ]
            elif isinstance(v, tuple):
                v = tuple(
                    _copy_expr(i, memo)
                    if isinstance(i, ex.EngineExpression)
                    else i
                    for i in v
                )
            setattr(new, slot, v)
    return new


def _remap_refs(expr: ex.EngineExpression, mapping: dict, seen: set) -> None:
    """Rewrite every ColumnRef.index through ``mapping`` (post-copy, so
    mutation is safe; ``seen`` guards shared subtrees)."""
    if id(expr) in seen:
        return
    seen.add(id(expr))
    if isinstance(expr, ex.ColumnRef):
        expr.index = mapping[expr.index]
        return
    for klass in type(expr).__mro__:
        for slot in getattr(klass, "__slots__", ()):
            try:
                v = getattr(expr, slot)
            except AttributeError:
                continue
            if isinstance(v, ex.EngineExpression):
                _remap_refs(v, mapping, seen)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    if isinstance(item, ex.EngineExpression):
                        _remap_refs(item, mapping, seen)


def _remap_consumer(consumer: g.Node, port: int, mapping: dict) -> None:
    if type(consumer) is g.ExpressionNode:
        memo: dict = {}
        seen: set = set()
        copied = [_copy_expr(e, memo) for e in consumer.expressions]
        for e in copied:
            _remap_refs(e, mapping, seen)
        consumer.expressions = copied
    elif type(consumer) is g.BatchApplyNode:
        consumer.arg_cols = [mapping[c] for c in consumer.arg_cols]
    elif type(consumer) is g.IxNode and port == 0:
        consumer.key_col = mapping[consumer.key_col]


def _apply_narrow(scope: g.Scope, index: int, keep: tuple, mapping: dict) -> None:
    node = scope.nodes[index]
    if type(node) is g.StaticSource:
        node._rows = [(k, tuple(r[c] for c in keep)) for k, r in node._rows]
    else:
        node.expressions = [node.expressions[c] for c in keep]
    node.arity = len(keep)
    for consumer, port in node.consumers:
        _remap_consumer(consumer, port, mapping)


def run(scopes: list, n_shared: int, protected: set) -> tuple[int, list[str]]:
    """Narrow dead producer columns across every replica scope.

    Returns ``(columns_dropped, fingerprint_entries)``.
    """
    primary = scopes[0]
    has_sinks = any(isinstance(n, g.SubscribeNode) for n in primary.nodes)
    if not (has_sinks or protected):
        return 0, []
    dropped = 0
    fingerprint: list[str] = []
    for node in reversed(primary.nodes):
        if node.index >= n_shared:
            continue
        if type(node) not in (g.StaticSource, g.ExpressionNode):
            continue
        if node.index in protected or getattr(node, "_pw_observed", False):
            continue
        if not node.consumers:
            continue
        refs: set[int] = set()
        ok = True
        for consumer, port in node.consumers:
            if consumer.index >= n_shared:
                ok = False
                break
            r = _consumer_refs(consumer, port)
            if r is None:
                ok = False
                break
            refs |= r
        if not ok or any(c >= node.arity for c in refs):
            continue
        keep = tuple(sorted(refs)) or (0,)  # keep at least one column
        if len(keep) >= node.arity:
            continue
        old_arity = node.arity
        mapping = {c: i for i, c in enumerate(keep)}
        for scope in scopes:
            _apply_narrow(scope, node.index, keep, mapping)
        dropped += old_arity - len(keep)
        fingerprint.append(
            "narrow:%d:%s:%d:%s"
            % (node.index, type(node).__name__, old_arity,
               ",".join(map(str, keep)))
        )
    return dropped, fingerprint
