"""Measurement-driven host/device placement for device-resident operators.

The optimizer's fourth pass — but unlike pushdown/elide/fuse it rewrites
nothing: it only *annotates* eligible operators (groupby, join, external
KNN index) and seeds the process-wide :data:`POLICY`, which then decides
host vs device per operator per batch at runtime from observed cost.

Why runtime and not plan time: the right placement depends on batch
size and on the actual device (a 200-row commit loses to kernel-launch
latency; a 2M-row commit wins by an order of magnitude), both of which
the plan cannot know.  The policy keeps an EMA of ns/row for each side
of each operator, bootstraps by probing both sides, then follows the
cheaper side with hysteresis (a side must win by 20% to flip the
decision) and a periodic re-probe of the losing side so a placement can
recover when batch shapes drift.

The pass is annotation-only on purpose: it runs even for graphs the
rewriting passes skip (external-index operators shadow ``node.index``,
which disables index-keyed rewrites — exactly the graphs the KNN
placement matters for), and it costs nothing when
``PATHWAY_TPU_DEVICE_OPS`` leaves device ops disabled (one cached env
check, then return).
"""

from __future__ import annotations

import os
import threading

__all__ = ["PlacementPolicy", "POLICY", "min_rows", "run_pass"]

#: EMA smoothing for observed ns/row
_ALPHA = 0.3


def min_rows() -> int:
    """Batches below this row count stay on host in auto mode — kernel
    launch latency dominates tiny commits (forced mode ignores this so
    CI exercises the kernels on toy batches)."""
    try:
        return max(
            0, int(os.environ.get("PATHWAY_TPU_DEVICE_OPS_MIN_ROWS", "512"))
        )
    except ValueError:
        return 512


class PlacementPolicy:
    """Per-operator host/device arbitration from observed kernel cost.

    Keyed by ``(op kind, operator position)`` — replicas of one operator
    across shards share a key, so their samples pool into one decision
    (the sharded scheduler runs replicas lockstep on one thread; the
    distributed scheduler pools per process, which is the granularity
    that owns a device).

    The enable/force/min-rows gates are pluggable so other device planes
    reuse the EMA/hysteresis machinery against their own env contract:
    the default instance (:data:`POLICY`) gates on
    ``PATHWAY_TPU_DEVICE_OPS``; the collective exchange
    (``engine/collective_exchange.py``) instantiates its own policy
    gated on ``PATHWAY_TPU_COLLECTIVE_EXCHANGE`` to learn per-edge
    device-vs-host exchange cost."""

    #: calls of each side to observe before judging
    PROBE_CALLS = 3
    #: a side must be this factor cheaper to flip the decision
    HYSTERESIS = 1.2
    #: re-probe the losing side every this many calls
    REPROBE_EVERY = 256

    def __init__(
        self,
        enabled_fn=None,
        forced_fn=None,
        min_rows_fn=None,
    ) -> None:
        self._lock = threading.Lock()
        self._stats: dict = {}
        self._enabled_fn = enabled_fn
        self._forced_fn = forced_fn
        self._min_rows_fn = min_rows_fn

    def _gates(self):
        """Resolve the (enabled, forced, min_rows) gate callables —
        lazily bound to device_ops for the default instance so importing
        this module never pulls the engine in."""
        if self._enabled_fn is None:
            from pathway_tpu.engine import device_ops as _dops

            self._enabled_fn = _dops.enabled
            self._forced_fn = _dops.forced
            self._min_rows_fn = min_rows
        return self._enabled_fn, self._forced_fn, self._min_rows_fn

    def _entry(self, key) -> dict:
        st = self._stats.get(key)
        if st is None:
            st = self._stats[key] = {
                "host_calls": 0,
                "device_calls": 0,
                "host_ns_per_row": None,
                "device_ns_per_row": None,
                "rows": 0,
                "device": False,
            }
        return st

    def seed(self, kind: str, index: int, device: bool | None = None) -> None:
        """Register an eligible operator (the optimizer pass calls this so
        ``decisions()`` lists every candidate before the first batch)."""
        with self._lock:
            st = self._entry((kind, index))
            if device is not None:
                st["device"] = device

    def choose(self, kind: str, index: int, n_rows: int) -> bool:
        """True → run this batch on device.  Called on the batch hot path,
        so the disabled case must stay one cached env check."""
        enabled_fn, forced_fn, min_rows_fn = self._gates()
        if not enabled_fn():
            return False
        if forced_fn():
            return True
        if n_rows < min_rows_fn():
            return False
        with self._lock:
            st = self._entry((kind, index))
            # bootstrap: measure both sides before judging
            if st["device_calls"] < self.PROBE_CALLS:
                return True
            if st["host_calls"] < self.PROBE_CALLS:
                return False
            total = st["host_calls"] + st["device_calls"]
            if total % self.REPROBE_EVERY == 0:
                return not st["device"]  # refresh the losing side's EMA
            d = st["device_ns_per_row"]
            h = st["host_ns_per_row"]
            if st["device"]:
                if d is not None and h is not None and h * self.HYSTERESIS < d:
                    st["device"] = False
            else:
                if d is not None and h is not None and d * self.HYSTERESIS < h:
                    st["device"] = True
            return st["device"]

    def record(
        self, kind: str, index: int, device: bool, n_rows: int, ns: int
    ) -> None:
        """Fold one observed execution into the EMA for its side."""
        per_row = float(ns) / max(1, n_rows)
        with self._lock:
            st = self._entry((kind, index))
            side = "device" if device else "host"
            st[side + "_calls"] += 1
            key = side + "_ns_per_row"
            prev = st[key]
            st[key] = (
                per_row
                if prev is None
                else (1.0 - _ALPHA) * prev + _ALPHA * per_row
            )
            st["rows"] += int(n_rows)
            if device and not st["device"] and st["host_calls"] == 0:
                # forced/bootstrap device runs count as a device placement
                st["device"] = True

    def decisions(self) -> dict:
        """Snapshot for cli stats / bench JSON: ``{"kind:index": {...}}``."""
        out = {}
        with self._lock:
            for (kind, index), st in sorted(
                self._stats.items(), key=lambda kv: (kv[0][0], kv[0][1])
            ):
                out[f"{kind}:{index}"] = {
                    "device": bool(st["device"]),
                    "host_calls": st["host_calls"],
                    "device_calls": st["device_calls"],
                    "host_ns_per_row": (
                        None
                        if st["host_ns_per_row"] is None
                        else round(st["host_ns_per_row"], 1)
                    ),
                    "device_ns_per_row": (
                        None
                        if st["device_ns_per_row"] is None
                        else round(st["device_ns_per_row"], 1)
                    ),
                    "rows": st["rows"],
                }
        return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


#: the process-wide policy every operator hook consults
POLICY = PlacementPolicy()


def run_pass(scopes: list) -> tuple[int, int]:
    """The optimizer's placement pass: annotate eligible operators and
    seed the policy.  Returns ``(eligible, placed_on_device)`` for the
    optimizer's stats surface.  Must cost ~nothing when device ops are
    disabled — that case is one cached env check."""
    from pathway_tpu.engine import device_ops as _dops

    if not _dops.enabled():
        return 0, 0
    from pathway_tpu.engine.graph import GroupbyNode, JoinNode

    force = _dops.forced()
    eligible = 0
    placed = 0
    seen: set = set()
    for scope in scopes:
        for pos, node in enumerate(scope.nodes):
            kind = None
            if isinstance(node, GroupbyNode):
                kind = "groupby"
            elif isinstance(node, JoinNode) and getattr(
                node, "_columnar_ok", False
            ):
                kind = "join"
            elif type(node).__name__ == "ExternalIndexNode":
                kind = "knn"
            if kind is None:
                continue
            node._device_ops_eligible = kind
            if (kind, pos) in seen:
                continue  # replica of an operator already counted
            seen.add((kind, pos))
            eligible += 1
            # KNN indexes are structurally placed (the factory chose the
            # engine); groupby/join start on device only when forced
            device = force or (
                kind == "knn"
                and type(getattr(node, "ext_index", None)).__name__
                == "DeviceKnnIndex"
            )
            POLICY.seed(kind, pos, device=device or None)
            if device:
                placed += 1
    return eligible, placed
