"""Measurement-driven host/device placement for device-resident operators.

The optimizer's fourth pass — but unlike pushdown/elide/fuse it rewrites
nothing: it only *annotates* eligible operators (groupby, join, external
KNN index) and seeds the process-wide :data:`POLICY`, which then decides
host vs device per operator per batch at runtime from observed cost.

Why runtime and not plan time: the right placement depends on batch
size and on the actual device (a 200-row commit loses to kernel-launch
latency; a 2M-row commit wins by an order of magnitude), both of which
the plan cannot know.  The policy keeps an EMA of ns/row for each side
of each operator, bootstraps by probing both sides, then follows the
cheaper side with hysteresis (a side must win by 20% to flip the
decision) and a periodic re-probe of the losing side so a placement can
recover when batch shapes drift.

Chain-aware placement (the device-residency seam): per-operator EMAs
alone under-place chains — a device groupby feeding a device join saves
a host materialization at the exchange seam between them, but neither
operator's own ns/row sees that saving.  The pass therefore links
adjacent device-eligible operators (:meth:`PlacementPolicy.link`), the
residency plane reports what each materialization at a consumer's seam
actually cost (:meth:`PlacementPolicy.record_seam`), and ``choose()``
credits that measured seam cost against the device side whenever a
linked neighbor currently sits on device and residency is enabled — so
consecutive device-eligible operators converge onto the device
together instead of each flapping on its solo margin.

The pass is annotation-only on purpose: it runs even for graphs the
rewriting passes skip (external-index operators shadow ``node.index``,
which disables index-keyed rewrites — exactly the graphs the KNN
placement matters for), and it costs nothing when
``PATHWAY_TPU_DEVICE_OPS`` leaves device ops disabled (one cached env
check, then return).
"""

from __future__ import annotations

import os
import threading

__all__ = ["PlacementPolicy", "POLICY", "min_rows", "run_pass"]

#: EMA smoothing for observed ns/row
_ALPHA = 0.3


def min_rows() -> int:
    """Batches below this row count stay on host in auto mode — kernel
    launch latency dominates tiny commits (forced mode ignores this so
    CI exercises the kernels on toy batches)."""
    try:
        return max(
            0, int(os.environ.get("PATHWAY_TPU_DEVICE_OPS_MIN_ROWS", "512"))
        )
    except ValueError:
        return 512


class PlacementPolicy:
    """Per-operator host/device arbitration from observed kernel cost.

    Keyed by ``(op kind, operator position)`` — replicas of one operator
    across shards share a key, so their samples pool into one decision
    (the sharded scheduler runs replicas lockstep on one thread; the
    distributed scheduler pools per process, which is the granularity
    that owns a device).

    The enable/force/min-rows gates are pluggable so other device planes
    reuse the EMA/hysteresis machinery against their own env contract:
    the default instance (:data:`POLICY`) gates on
    ``PATHWAY_TPU_DEVICE_OPS``; the collective exchange
    (``engine/collective_exchange.py``) instantiates its own policy
    gated on ``PATHWAY_TPU_COLLECTIVE_EXCHANGE`` to learn per-edge
    device-vs-host exchange cost."""

    #: calls of each side to observe before judging
    PROBE_CALLS = 3
    #: a side must be this factor cheaper to flip the decision
    HYSTERESIS = 1.2
    #: re-probe the losing side every this many calls
    REPROBE_EVERY = 256

    def __init__(
        self,
        enabled_fn=None,
        forced_fn=None,
        min_rows_fn=None,
    ) -> None:
        self._lock = threading.Lock()
        self._stats: dict = {}  # guarded-by: _lock
        self._links: dict = {}  # guarded-by: _lock — key -> set of adjacent keys
        self._enabled_fn = enabled_fn
        self._forced_fn = forced_fn
        self._min_rows_fn = min_rows_fn

    def _gates(self):
        """Resolve the (enabled, forced, min_rows) gate callables —
        lazily bound to device_ops for the default instance so importing
        this module never pulls the engine in."""
        if self._enabled_fn is None:
            from pathway_tpu.engine import device_ops as _dops

            self._enabled_fn = _dops.enabled
            self._forced_fn = _dops.forced
            self._min_rows_fn = min_rows
        return self._enabled_fn, self._forced_fn, self._min_rows_fn

    def _entry_locked(self, key) -> dict:
        st = self._stats.get(key)
        if st is None:
            st = self._stats[key] = {
                "host_calls": 0,
                "device_calls": 0,
                "host_ns_per_row": None,
                "device_ns_per_row": None,
                "seam_ns_per_row": None,
                "seam_events": 0,
                "rows": 0,
                "device": False,
            }
        return st

    def seed(self, kind: str, index: int, device: bool | None = None) -> None:
        """Register an eligible operator (the optimizer pass calls this so
        ``decisions()`` lists every candidate before the first batch)."""
        with self._lock:
            st = self._entry_locked((kind, index))
            if device is not None:
                st["device"] = device

    def link(
        self, kind_a: str, index_a: int, kind_b: str, index_b: int
    ) -> None:
        """Declare two eligible operators adjacent (producer feeds
        consumer through an exchange seam).  Links are symmetric: either
        end being on device makes residency possible across the seam, so
        either end earns the chain credit for joining it."""
        a, b = (kind_a, index_a), (kind_b, index_b)
        if a == b:
            return
        with self._lock:
            self._entry_locked(a)
            self._entry_locked(b)
            self._links.setdefault(a, set()).add(b)
            self._links.setdefault(b, set()).add(a)

    def record_seam(
        self, kind: str, index: int, n_rows: int, ns: int
    ) -> None:
        """Fold one observed seam materialization (a resident batch
        fetched to host at this consumer) into the seam-cost EMA — this
        is the transfer a colocated device placement would have saved."""
        per_row = float(ns) / max(1, n_rows)
        with self._lock:
            st = self._entry_locked((kind, index))
            st["seam_events"] += 1
            prev = st["seam_ns_per_row"]
            st["seam_ns_per_row"] = (
                per_row
                if prev is None
                else (1.0 - _ALPHA) * prev + _ALPHA * per_row
            )

    def is_device(self, kind: str, index: int) -> bool:
        """Current placement of an operator (False for unknown keys) —
        the residency plane consults this in auto mode so exchange
        outputs only stay resident for consumers that will actually run
        on device."""
        with self._lock:
            st = self._stats.get((kind, index))
            return bool(st and st["device"])

    def _chain_credit(self, key: tuple, st: dict) -> float:
        """ns/row credited to the device side of ``key`` for seam
        transfers residency would save.  Non-zero only when residency is
        enabled and a linked neighbor currently sits on device; the
        magnitude is this operator's own measured seam EMA (what each
        host materialization at its input actually cost).  Caller holds
        ``self._lock``."""
        links = self._links.get(key)
        if not links:
            return 0.0
        seam = st["seam_ns_per_row"]
        if not seam:
            return 0.0
        if not any(
            n in self._stats and self._stats[n]["device"] for n in links
        ):
            return 0.0
        if not _residency_on():
            return 0.0
        return float(seam)

    def choose(self, kind: str, index: int, n_rows: int) -> bool:
        """True → run this batch on device.  Called on the batch hot path,
        so the disabled case must stay one cached env check."""
        enabled_fn, forced_fn, min_rows_fn = self._gates()
        if not enabled_fn():
            return False
        if forced_fn():
            return True
        if n_rows < min_rows_fn():
            return False
        with self._lock:
            st = self._entry_locked((kind, index))
            # bootstrap: measure both sides before judging
            if st["device_calls"] < self.PROBE_CALLS:
                return True
            if st["host_calls"] < self.PROBE_CALLS:
                return False
            total = st["host_calls"] + st["device_calls"]
            if total % self.REPROBE_EVERY == 0:
                return not st["device"]  # refresh the losing side's EMA
            d = st["device_ns_per_row"]
            h = st["host_ns_per_row"]
            if d is not None:
                # chain-aware: device placement next to a device-placed
                # neighbor saves the seam materialization — score it in
                d = max(0.0, d - self._chain_credit((kind, index), st))
            if st["device"]:
                if d is not None and h is not None and h * self.HYSTERESIS < d:
                    st["device"] = False
            else:
                if d is not None and h is not None and d * self.HYSTERESIS < h:
                    st["device"] = True
            return st["device"]

    def record(
        self, kind: str, index: int, device: bool, n_rows: int, ns: int
    ) -> None:
        """Fold one observed execution into the EMA for its side."""
        per_row = float(ns) / max(1, n_rows)
        with self._lock:
            st = self._entry_locked((kind, index))
            side = "device" if device else "host"
            st[side + "_calls"] += 1
            key = side + "_ns_per_row"
            prev = st[key]
            st[key] = (
                per_row
                if prev is None
                else (1.0 - _ALPHA) * prev + _ALPHA * per_row
            )
            st["rows"] += int(n_rows)
            if device and not st["device"] and st["host_calls"] == 0:
                # forced/bootstrap device runs count as a device placement
                st["device"] = True

    def decisions(self) -> dict:
        """Snapshot for cli stats / bench JSON: ``{"kind:index": {...}}``."""
        out = {}
        with self._lock:
            for (kind, index), st in sorted(
                self._stats.items(), key=lambda kv: (kv[0][0], kv[0][1])
            ):
                out[f"{kind}:{index}"] = {
                    "device": bool(st["device"]),
                    "host_calls": st["host_calls"],
                    "device_calls": st["device_calls"],
                    "host_ns_per_row": (
                        None
                        if st["host_ns_per_row"] is None
                        else round(st["host_ns_per_row"], 1)
                    ),
                    "device_ns_per_row": (
                        None
                        if st["device_ns_per_row"] is None
                        else round(st["device_ns_per_row"], 1)
                    ),
                    "seam_ns_per_row": (
                        None
                        if st["seam_ns_per_row"] is None
                        else round(st["seam_ns_per_row"], 1)
                    ),
                    "seam_events": st["seam_events"],
                    "links": sorted(
                        f"{k}:{i}"
                        for (k, i) in self._links.get((kind, index), ())
                    ),
                    "rows": st["rows"],
                }
        return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._links.clear()


def _residency_on() -> bool:
    """Lazy gate on the residency plane (import-cycle-free: residency
    imports this module's POLICY inside functions only)."""
    try:
        from pathway_tpu.engine import device_residency as _dres

        return _dres.enabled()
    except Exception:  # pragma: no cover — residency plane unavailable
        return False


#: the process-wide policy every operator hook consults
POLICY = PlacementPolicy()


def run_pass(scopes: list) -> tuple[int, int]:
    """The optimizer's placement pass: annotate eligible operators and
    seed the policy.  Returns ``(eligible, placed_on_device)`` for the
    optimizer's stats surface.  Must cost ~nothing when device ops are
    disabled — that case is one cached env check."""
    from pathway_tpu.engine import device_ops as _dops

    if not _dops.enabled():
        return 0, 0
    from pathway_tpu.engine.graph import GroupbyNode, JoinNode

    force = _dops.forced()
    eligible = 0
    placed = 0
    seen: set = set()
    for scope in scopes:
        for pos, node in enumerate(scope.nodes):
            kind = None
            if isinstance(node, GroupbyNode):
                kind = "groupby"
            elif isinstance(node, JoinNode) and getattr(
                node, "_columnar_ok", False
            ):
                kind = "join"
            elif type(node).__name__ == "ExternalIndexNode":
                kind = "knn"
            if kind is None:
                continue
            node._device_ops_eligible = kind
            if (kind, pos) in seen:
                continue  # replica of an operator already counted
            seen.add((kind, pos))
            eligible += 1
            # KNN indexes are structurally placed (the factory chose the
            # engine); groupby/join start on device only when forced
            device = force or (
                kind == "knn"
                and type(getattr(node, "ext_index", None)).__name__
                == "DeviceKnnIndex"
            )
            POLICY.seed(kind, pos, device=device or None)
            if device:
                placed += 1
    # second walk (after every operator is annotated): link each eligible
    # operator to the next eligible operator downstream — through any
    # non-eligible pass-through nodes, bounded because seams are local —
    # so choose() can credit the residency saving across the exchange
    # seam between them.  The same sweep marks each traversed
    # intermediate with its downstream eligible operator
    # (``_device_residency_downstream``): repartitions often land on a
    # row-local expression/filter stage directly feeding the stateful
    # operator (the pushdown pass moves the exchange above them), and a
    # resident delivery into that stage belongs to the operator's seam.
    # Later fusion mutates a chain tail's ``__class__`` in place, so the
    # attribute survives onto the FusedChainNode the scheduler delivers
    # to.
    for scope in scopes:
        for node in scope.nodes:
            kind = getattr(node, "_device_ops_eligible", None)
            if kind is None:
                continue
            # upstream: mark feeders of this operator (bounded)
            up = [(inp, 0) for inp in node.inputs]
            seen_up: set = set()
            while up:
                prev, depth = up.pop()
                if id(prev) in seen_up or depth > 4:
                    continue
                seen_up.add(id(prev))
                if getattr(prev, "_device_ops_eligible", None) is not None:
                    continue
                if getattr(
                    prev, "_device_residency_downstream", None
                ) is None:
                    prev._device_residency_downstream = (kind, node.index)
                up.extend((i, depth + 1) for i in prev.inputs)
            # downstream: link to the next eligible operator (bounded)
            frontier = [(c, 0) for c, _port in node.consumers]
            visited: set = set()
            while frontier:
                nxt, depth = frontier.pop()
                if id(nxt) in visited or depth > 4:
                    continue
                visited.add(id(nxt))
                ckind = getattr(nxt, "_device_ops_eligible", None)
                if ckind is not None:
                    POLICY.link(kind, node.index, ckind, nxt.index)
                    continue
                frontier.extend(
                    (c, depth + 1) for c, _port in nxt.consumers
                )
    return eligible, placed
