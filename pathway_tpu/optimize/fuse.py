"""Stateless-chain fusion — collapse Expression/Filter runs into one node.

A *chain* is a maximal linear run ``n1 -> n2 -> ... -> nk`` (k >= 2) of
exact-type :class:`ExpressionNode` / :class:`FilterNode` operators where
every non-tail member has exactly one consumer (the next member, on port
0) and is neither externally observed (``_pw_observed``, capture targets)
nor protected (cross-process sink consumers, sink-region edges).  The tail
is mutated in place into a :class:`FusedChainNode` that evaluates the
whole chain in one columnar sweep per :class:`DeltaBatch`; interior
members become inert placeholders so every ``node.index`` keeps matching
its position in ``scope.nodes`` — the invariant the sharded schedulers
use to address replicas.

Correctness rests on two properties of the fused member kinds:

- insert processing is *stateless* (Expression evaluates, Filter drops),
  so composing the per-row transforms is literal function composition and
  interior nodes need no state maintenance;
- deletions are retracted from a node's *own* output state, and both
  kinds are key-preserving, so retracting once from the tail's state is
  identical to the unfused cascade — a key survives the tail's state iff
  it passed every interior filter — even for nondeterministic UDFs (the
  same argument ExpressionNode.process makes for itself).

Errors are reported through the *original* stage node objects (kept
inside ``_stages``), so error-log names and traces match the unfused
graph exactly.
"""

from __future__ import annotations

import numpy as np

from pathway_tpu.engine import device
from pathway_tpu.engine import graph as g
from pathway_tpu.engine.batch import Columns, DeltaBatch
from pathway_tpu.engine.expression import EvalContext
from pathway_tpu.engine.value import Pointer, is_error

#: exact types (not subclasses) eligible for chain membership
_MEMBER_TYPES = (g.ExpressionNode, g.FilterNode)


class _ArrayView:
    """Columnar view over already-evaluated stage arrays (mid-chain rows)."""

    __slots__ = ("arrays", "n")

    def __init__(self, arrays: list, n: int) -> None:
        self.arrays = arrays
        self.n = n

    def column(self, i: int):
        a = self.arrays[i]
        return a if a.dtype.kind in "bifU" else None


class _SelView:
    """Row subset of an input view (filters applied before the first
    expression stage); gathered columns are cached per index."""

    __slots__ = ("_base", "_sel", "_cache", "n")

    def __init__(self, base, sel: np.ndarray) -> None:
        self._base = base
        self._sel = sel
        self._cache: dict = {}
        self.n = int(len(sel))

    def column(self, i: int):
        got = self._cache.get(i, False)
        if got is not False:
            return got
        col = self._base.column(i)
        if col is not None:
            col = col[self._sel]
        self._cache[i] = col
        return col


class FusedChainNode(g.Node):
    """A fused Expression/Filter chain.

    Never constructed directly: :func:`apply_chain` mutates the chain
    tail's ``__class__`` so the node keeps its index, arity, name and
    state dict.  ``_stages`` holds ``("expr", node, expressions)`` /
    ``("filter", node, condition_col)`` descriptors built from the
    original member nodes.
    """

    STATE_ATTRS = ()

    def process(self, time: int) -> DeltaBatch:
        from pathway_tpu.internals import tracing as _tracing

        batch = self.take_raw(0)
        if not (batch._insert_only or batch._raw_insert_only):
            batch = batch.consolidate()
        insert_only = batch._insert_only or batch._raw_insert_only
        trace = _tracing.current()
        if insert_only and len(batch) >= device.VECTOR_THRESHOLD:
            if trace is not None:
                import time as _walltime

                t0 = _walltime.perf_counter()
                fast = self._columnar_sweep(batch)
                if fast is not None:
                    trace.span(
                        f"fused-sweep:{getattr(self, 'name', '') or self.index}",
                        "op",
                        t0,
                        _walltime.perf_counter(),
                        mode="columnar",
                        rows=len(batch),
                        stages=len(self._stages),
                    )
                    return fast
            else:
                fast = self._columnar_sweep(batch)
                if fast is not None:
                    return fast
        out = DeltaBatch()
        if not insert_only:
            state = self.current  # tail output state: retract once, up front
            for key, row, diff in batch:
                if diff < 0:
                    prev = state.get(key)
                    if prev is not None:
                        out.append(key, prev, diff)
        inserts = (
            batch.entries if insert_only else [e for e in batch if e[2] > 0]
        )
        for key, row, diff in self._staged_rows(inserts):
            out.append(key, row, diff)
        return out

    # -- row fallback --------------------------------------------------------

    def _staged_rows(self, rows: list) -> list:
        """Run the insert list through every stage in order; errors report
        via the stage's original node (names/traces match unfused runs)."""
        for kind, stage, spec in self._stages:
            if not rows:
                break
            if kind == "expr":
                ctx = EvalContext()
                rows = [
                    (key, tuple(e.evaluate(key, row, ctx) for e in spec), diff)
                    for key, row, diff in rows
                ]
                for key, message in ctx.errors:
                    stage.report(key, message)
            else:
                kept = []
                for key, row, diff in rows:
                    cond = row[spec]
                    if is_error(cond):
                        stage.report(key, "error value in filter condition")
                        continue
                    if cond:
                        kept.append((key, row, diff))
                rows = kept
        return rows

    # -- columnar sweep ------------------------------------------------------

    @staticmethod
    def _entry_kbytes(entries: list):
        from pathway_tpu.native import kernels as _native

        if _native is not None:
            return _native.entry_keys_bytes(entries, Pointer)
        return g._entry_keys_bytes_py(entries)

    def _columnar_sweep(self, batch: DeltaBatch) -> DeltaBatch | None:
        """Insert-only batch through the whole chain without materialising
        any intermediate batch; None falls back to the row path."""
        payload = batch.columns
        entries = None
        if payload is not None:
            view = device.PayloadView(payload)
        else:
            entries = batch.entries
            view = device.ColumnarView(entries, from_entries=True)
        arrays: list | None = None  # None => rows still have the input layout
        sel: np.ndarray | None = None  # surviving original-row indices
        n_cur = view.n
        for kind, _stage, spec in self._stages:
            if n_cur == 0:
                break
            if kind == "expr":
                if arrays is None:
                    cur = view if sel is None else _SelView(view, sel)
                else:
                    cur = _ArrayView(arrays, n_cur)
                nxt = []
                for expr in spec:
                    try:
                        nxt.append(device.eval_columnar(expr, cur))
                    except device.NotVectorizable:
                        return None
                arrays = nxt
            else:
                if arrays is None:
                    cur = view if sel is None else _SelView(view, sel)
                    cond = cur.column(spec)
                else:
                    cond = arrays[spec]
                if cond is None or cond.dtype.kind != "b":
                    return None
                if cond.all():
                    continue
                if arrays is not None:
                    arrays = [a[cond] for a in arrays]
                sel = np.flatnonzero(cond) if sel is None else sel[cond]
                n_cur = int(len(sel))
        if n_cur == 0:
            return DeltaBatch()
        hint = batch._insert_only
        if arrays is None:
            # pure-filter chain: the original rows survive at ``sel``
            if payload is not None:
                cols = payload if sel is None else payload.gather(sel)
                out = DeltaBatch.from_columns(
                    cols, consolidated=hint, insert_only=hint
                )
                out._raw_insert_only = batch._raw_insert_only or out._insert_only
                return out
            out = DeltaBatch()
            out.entries = (
                list(entries) if sel is None else [entries[i] for i in sel]
            )
            out._consolidated = hint
            out._insert_only = hint
            out._raw_insert_only = True
            return out
        if sel is None:
            if payload is not None:
                out_payload = Columns.with_keys_of(payload, arrays)
            else:
                kb = self._entry_kbytes(entries)
                if kb is None:
                    return None  # non-Pointer keys: row path
                out_payload = Columns(n_cur, arrays, kbytes=kb)
        else:
            kobjs = None
            if payload is not None:
                kb, kobjs = payload.keys_gather(sel)
            else:
                kb = self._entry_kbytes(entries)
                if kb is None:
                    return None
                kb = kb[sel]
            out_payload = Columns(n_cur, arrays, kbytes=kb, kobjs=kobjs)
        out = DeltaBatch.from_columns(
            out_payload, consolidated=hint, insert_only=hint
        )
        out._raw_insert_only = batch._raw_insert_only or out._insert_only
        return out


# -- chain discovery / application ------------------------------------------


def _observed(node: g.Node) -> bool:
    return bool(getattr(node, "_pw_observed", False))


def _link(node: g.Node, n_shared: int, protected: set) -> g.Node | None:
    """The unique next chain member after ``node``, or None.

    ``node`` must be fusable *as a non-tail member*: exact member type,
    inside the shared region, unobserved/unprotected, and consumed by
    exactly one node which is itself a member candidate.
    """
    if type(node) not in _MEMBER_TYPES or node.index >= n_shared:
        return None
    if node.index in protected or _observed(node):
        return None
    if len(node.consumers) != 1:
        return None
    nxt, port = node.consumers[0]
    if port != 0 or type(nxt) not in _MEMBER_TYPES or nxt.index >= n_shared:
        return None
    return nxt


def find_chains(scope: g.Scope, n_shared: int, protected: set) -> list[list[int]]:
    """Maximal fusable chains on the primary scope, as index lists (>= 2)."""
    link: dict[int, int] = {}
    for node in scope.nodes:
        nxt = _link(node, n_shared, protected)
        if nxt is not None:
            link[node.index] = nxt.index
    linked_to = set(link.values())
    chains = []
    for node in scope.nodes:
        idx = node.index
        if idx not in link or idx in linked_to:
            continue
        chain = [idx]
        while idx in link:
            idx = link[idx]
            chain.append(idx)
        chains.append(chain)
    return chains


def apply_chain(scope: g.Scope, chain: list[int]) -> g.Node:
    """Mutate one replica scope in place: the tail becomes the
    FusedChainNode, interiors become inert placeholders (indices kept)."""
    nodes = scope.nodes
    members = [nodes[i] for i in chain]
    head, tail = members[0], members[-1]
    stages = []
    for m in members:
        if type(m) is g.ExpressionNode:
            stages.append(("expr", m, list(m.expressions)))
        else:
            stages.append(("filter", m, m.condition_col))
    producer = head.inputs[0]
    producer.consumers = [
        (tail, p) if (c is head and p == 0) else (c, p)
        for c, p in producer.consumers
    ]
    tail.__class__ = FusedChainNode
    tail._stages = stages
    tail.inputs = [producer]
    for m in members[:-1]:
        m.inputs = []
        m.consumers = []
        m.pending = {}
        m._pw_fused_into = tail.index
    return tail
