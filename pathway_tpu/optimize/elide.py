"""Exchange elision — mark provably redundant producer->consumer edges.

The oracle is :func:`pathway_tpu.analysis.shards.redundant_edges` — the
exact edge set the analyzer reports as PWA201 — so the analyzer and the
rewriter can never disagree (a test asserts the counts match).  Marks are
computed on the post-pushdown, pre-fusion graph; pushdown cannot change
the set (it only narrows Expression/StaticSource producers, whose
out-specs are arity-independent), and fusion only *renames* edges:

- an edge into a chain head moves to the fused tail (the tail inherits
  the head's input port and the head's ``("key",)`` arrival rule);
- an intra-chain edge disappears from the runtime set entirely — the
  exchange it crossed has been fused away, the strongest form of elision
  (it still counts in ``optimizer_stats()["exchanges_elided"]``).

At delivery time the sharded/distributed schedulers check the returned
``(producer_index, consumer_index, port)`` set *before* running routing
digests: a marked edge pushes the whole batch to the co-located replica,
skipping ``columnar_shards``/``entry_shards`` and, on the TCP mesh, the
PWCF encode/decode round-trip.  Elision also outranks the device
collective plane (engine/collective_exchange.py): an elided edge never
reaches the collective consult — the cheapest exchange is the one that
does not happen, on host OR device.
"""

from __future__ import annotations

from pathway_tpu.analysis.shards import redundant_edges


def plan(scope, n_shared: int) -> set[tuple[int, int, int]]:
    """Elidable edges on the primary scope, restricted to the shared
    (replicated) node region."""
    marks = set()
    for prod, cons, port, _rule in redundant_edges(scope):
        if prod < n_shared and cons < n_shared:
            marks.add((prod, cons, port))
    return marks


def remap_through_fusion(
    marks: set[tuple[int, int, int]], chains: list[list[int]]
) -> set[tuple[int, int, int]]:
    """Rewrite pre-fusion marks into the post-fusion runtime set."""
    head_tail: dict[int, int] = {}
    member_chain: dict[int, int] = {}
    for ci, chain in enumerate(chains):
        head_tail[chain[0]] = chain[-1]
        for idx in chain:
            member_chain[idx] = ci
    out = set()
    for prod, cons, port in marks:
        pc = member_chain.get(prod)
        cc = member_chain.get(cons)
        if pc is not None and cc is not None and pc == cc:
            continue  # intra-chain: fused away entirely
        if cc is not None:
            # only the head receives external input; the edge now lands on
            # the fused tail
            cons = chains[cc][-1]
        if pc is not None and prod != chains[pc][-1]:
            continue  # interior producers no longer emit
        out.add((prod, cons, port))
    return out
