"""Pre-execution DAG rewriter: the analyzer's findings, acted on.

Runs between graph capture and scheduling — every scheduler constructor
calls :func:`optimize_scopes` on its replica scopes before any batch
flows.  Three passes, in order:

1. **projection pushdown** (:mod:`pathway_tpu.optimize.pushdown`) —
   narrow StaticSource/Expression producers to the columns their
   consumers actually read (the PWA101 dead-column set), shrinking every
   downstream tuple, shard frame and checkpoint;
2. **exchange elision** (:mod:`pathway_tpu.optimize.elide`) — mark the
   provably redundant exchange edges (the PWA201 set) so the sharded and
   distributed schedulers deliver those batches straight to the
   co-located replica;
3. **stateless-chain fusion** (:mod:`pathway_tpu.optimize.fuse`) —
   collapse linear Expression/Filter runs into one FusedChainNode
   evaluating the whole chain in a single columnar sweep per batch;
4. **device placement** (:mod:`pathway_tpu.optimize.placement`) —
   annotation-only: mark the operators eligible for the JAX device
   kernels (groupby segment reduction, join pair matcher, external KNN
   index) and seed the measurement-driven placement policy that
   arbitrates host vs device per batch at runtime.  Unlike the
   rewriting passes it also runs on graphs whose operators shadow
   ``node.index`` (external indexes), since it never keys a rewrite off
   the index; it is a no-op unless ``PATHWAY_TPU_DEVICE_OPS`` enables
   device ops.

All rewrites mutate the node list *in place* and never add or remove
list slots: ``node.index == position`` is the invariant the sharded
schedulers address replicas by, so fused interiors stay behind as inert
placeholders.

Control knobs: ``PATHWAY_TPU_OPTIMIZE=0`` disables every pass (the
escape hatch, exercised by ``tools/check.py``); analyze mode
(``PATHWAY_TPU_ANALYZE=1``) also disables them so ``cli analyze``
reports on the graph the user wrote, not the rewritten one.
"""

from __future__ import annotations

import os

from pathway_tpu.optimize import elide as _elide
from pathway_tpu.optimize import fuse as _fuse
from pathway_tpu.optimize import placement as _placement
from pathway_tpu.optimize import pushdown as _pushdown
from pathway_tpu.optimize.fuse import FusedChainNode

__all__ = [
    "FusedChainNode",
    "enabled",
    "optimize_scopes",
    "optimizer_stats",
]

_ZERO_STATS = {
    "chains_fused": 0,
    "nodes_fused": 0,
    "columns_dropped": 0,
    "exchanges_elided": 0,
}

#: counters from the most recent optimize_scopes() run in this process
_LAST_STATS = dict(_ZERO_STATS)


def enabled() -> bool:
    """True unless ``PATHWAY_TPU_OPTIMIZE`` turns the rewriter off."""
    return os.environ.get("PATHWAY_TPU_OPTIMIZE", "1").strip().lower() not in (
        "0",
        "false",
        "off",
    )


def optimizer_stats() -> dict:
    """Counters from the most recent :func:`optimize_scopes` run:
    ``chains_fused``, ``nodes_fused``, ``columns_dropped``,
    ``exchanges_elided``."""
    return dict(_LAST_STATS)


def optimize_scopes(
    scopes: list, n_shared: int | None = None, protected=()
) -> set[tuple[int, int, int]]:
    """Rewrite the replica ``scopes`` in place; idempotent per graph.

    ``scopes[0]`` is the primary (decision) scope; every rewrite is
    replayed on the other replicas by node index.  ``n_shared`` bounds
    the region replicated across workers/processes (the primary may carry
    extra trailing sink nodes); ``protected`` adds node indices with
    consumers this process cannot see (distributed followers pass the
    announced sink-edge producers).

    Returns the runtime exchange-elision set of
    ``(producer_index, consumer_index, port)`` triples.
    """
    global _LAST_STATS
    primary = scopes[0]
    done = getattr(primary, "_pw_opt_elided", None)
    if done is not None:
        return done
    from pathway_tpu.analysis import runtime as _aruntime

    if not enabled() or _aruntime.enabled():
        _LAST_STATS = dict(_ZERO_STATS)  # "last run" applied no rewrites
        return set()
    # placement is annotation-only, so it may run before the index guard
    # below — external-index graphs are skipped by the rewrites but are
    # exactly where KNN placement applies
    dev_eligible, dev_placed = _placement.run_pass(scopes)
    dev_stats = {
        "device_eligible": dev_eligible,
        "device_placed": dev_placed,
    }
    for i, node in enumerate(primary.nodes):
        if not (isinstance(node.index, int) and node.index == i):
            # external-index/device operators shadow ``.index`` with their
            # index object, and every rewrite replay and elision triple
            # keys off ``node.index == position`` — leave such graphs
            # untouched (their operators also peek at input state in ways
            # the rewrites must not disturb)
            _LAST_STATS = dict(_ZERO_STATS, **dev_stats)
            primary._pw_opt_fingerprint = []
            primary._pw_opt_elided = set()
            return primary._pw_opt_elided
    if n_shared is None:
        n_shared = min(len(s.nodes) for s in scopes)
    protected = set(protected)
    for node in primary.nodes[:n_shared]:
        if any(c.index >= n_shared for c, _p in node.consumers):
            protected.add(node.index)

    dropped, fingerprint = _pushdown.run(scopes, n_shared, protected)
    marks = _elide.plan(primary, n_shared)
    chains = _fuse.find_chains(primary, n_shared, protected)
    runtime_marks = _elide.remap_through_fusion(marks, chains)
    for scope in scopes:
        for chain in chains:
            _fuse.apply_chain(scope, chain)
    for chain in chains:
        fingerprint.append("fuse:" + ",".join(map(str, chain)))
    if marks:
        fingerprint.append(
            "elide:" + ";".join("%d>%d.%d" % m for m in sorted(marks))
        )

    stats = {
        "chains_fused": len(chains),
        "nodes_fused": sum(len(c) for c in chains),
        "columns_dropped": dropped,
        "exchanges_elided": len(marks),
        **dev_stats,
    }
    primary._pw_opt_stats = stats
    primary._pw_opt_fingerprint = fingerprint
    primary._pw_opt_elided = runtime_marks
    _LAST_STATS = stats
    return runtime_marks
