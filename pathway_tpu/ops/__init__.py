"""Device ops: the JAX/XLA/Pallas compute kernels of the framework.

The reference keeps per-row interpreted math in Rust (src/engine/expression.rs)
and vector search in CPU libraries (usearch / brute-force loops,
src/external_integration/). Here the hot ops live in HBM and run on the MXU:
fixed-capacity masked KNN (ops/knn.py), attention (parallel/ring_attention.py),
and the model layers (models/). Everything is jit-compiled with static shapes
— dynamic row counts are bucket-padded by the callers.
"""

from pathway_tpu.ops.flash_attention import flash_attention
from pathway_tpu.ops.knn import (
    DeviceKnnState,
    knn_init,
    knn_search,
    knn_search_sharded,
    knn_update,
    shard_state,
)

__all__ = [
    "DeviceKnnState",
    "flash_attention",
    "knn_init",
    "knn_search",
    "knn_search_sharded",
    "knn_update",
    "shard_state",
]
