"""Fixed-capacity brute-force KNN resident in TPU HBM.

TPU-native replacement for the reference's CPU brute-force index
(reference: src/external_integration/brute_force_knn_integration.rs:70-113 —
dense matrix + norm loops) and the role usearch HNSW plays for as-of-now
retrieval. Design:

- The index is a *fixed-capacity slot array* ``[capacity, dim]`` with a
  validity mask — adds/removes are scatter updates into donated buffers, so
  mutation never reallocates or recompiles (static shapes; the host keeps the
  slot <-> key mapping).
- Search is one big masked matmul on the MXU followed by ``lax.top_k`` —
  exactly the shape XLA tiles best, and at ~1M x 384 it saturates HBM
  bandwidth rather than compute, which is the right regime for streaming
  ingest+query.
- Sharding: the capacity axis is laid out over the ``data`` mesh axis
  (see ``shard_state``); queries are replicated, local top-k per shard is
  merged with a second tiny top-k — the collective is an all-gather of
  ``[q, k]`` candidates over ICI, not the full score matrix.

Metrics match the reference's MetricKind subset: cosine, l2sq, dot
(usearch_integration.rs:20).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.parallel.mesh import DATA_AXIS, axis_size as mesh_axis_size

METRICS = ("cos", "l2sq", "dot")


class DeviceKnnState(NamedTuple):
    """Device-resident index state (a pytree; donate on update)."""

    vectors: jax.Array  # [capacity, dim]
    valid: jax.Array  # [capacity] bool
    norms: jax.Array  # [capacity] float32 — squared L2 norms, for l2sq


def knn_init(
    capacity: int,
    dim: int,
    dtype: jnp.dtype = jnp.float32,
    *,
    mesh: Mesh | None = None,
) -> DeviceKnnState:
    """Allocate an empty index; optionally sharded over the data axis."""
    state = DeviceKnnState(
        vectors=jnp.zeros((capacity, dim), dtype),
        valid=jnp.zeros((capacity,), jnp.bool_),
        norms=jnp.zeros((capacity,), jnp.float32),
    )
    if mesh is not None:
        state = shard_state(state, mesh)
    return state


def shard_state(state: DeviceKnnState, mesh: Mesh) -> DeviceKnnState:
    """Lay the capacity axis over the data mesh axis (HBM-sharded index)."""
    vec_sh = NamedSharding(mesh, P(DATA_AXIS, None))
    row_sh = NamedSharding(mesh, P(DATA_AXIS))
    return DeviceKnnState(
        vectors=jax.device_put(state.vectors, vec_sh),
        valid=jax.device_put(state.valid, row_sh),
        norms=jax.device_put(state.norms, row_sh),
    )


@functools.partial(jax.jit, donate_argnums=0)
def knn_update(
    state: DeviceKnnState,
    slots: jax.Array,  # [b] int32 — slot per row
    vectors: jax.Array,  # [b, dim]
    set_valid: jax.Array,  # [b] bool — True = insert, False = delete
    enabled: jax.Array,  # [b] bool — padding rows are disabled
) -> DeviceKnnState:
    """Scatter a batch of adds/removes into the slot array.

    The host allocator picks slots (free list) and pads batches to bucketed
    sizes; disabled rows scatter to slot ``capacity`` (dropped).

    Precondition: enabled slots must be unique within a batch — XLA scatter
    leaves the winner unspecified on duplicates. The host side (stdlib
    indexing) consolidates updates per key per commit, so a delete+reinsert
    of one key arrives as a single insert to a fresh slot.
    """
    capacity = state.vectors.shape[0]
    slots = jnp.where(enabled, slots, capacity)
    vecs = vectors.astype(state.vectors.dtype)
    new_vectors = state.vectors.at[slots].set(vecs, mode="drop")
    new_valid = state.valid.at[slots].set(set_valid, mode="drop")
    sq = jnp.sum(vectors.astype(jnp.float32) ** 2, axis=-1)
    new_norms = state.norms.at[slots].set(sq, mode="drop")
    return DeviceKnnState(new_vectors, new_valid, new_norms)


def _scores(
    state: DeviceKnnState, queries: jax.Array, metric: str
) -> jax.Array:
    """Higher-is-better scores ``[q, capacity]`` with invalid slots masked."""
    q = queries.astype(jnp.float32)
    db = state.vectors.astype(jnp.float32)
    # HIGHEST: TPU's default f32 matmul runs bf16 multiply passes, which
    # alone costs ~4% top-10 overlap vs exact host search; the score
    # matmul is tiny relative to embedding, so full precision is free
    dots = jnp.einsum(
        "qd,cd->qc", q, db, precision=lax.Precision.HIGHEST
    )
    if metric == "dot":
        scores = dots
    elif metric == "cos":
        qn = jnp.sqrt(jnp.sum(q * q, axis=-1, keepdims=True))
        dbn = jnp.sqrt(state.norms)[None, :]
        scores = dots / jnp.maximum(qn * dbn, 1e-30)
    elif metric == "l2sq":
        qn = jnp.sum(q * q, axis=-1, keepdims=True)
        scores = -(qn + state.norms[None, :] - 2.0 * dots)
    else:
        raise ValueError(f"unknown metric {metric!r}")
    return jnp.where(state.valid[None, :], scores, -jnp.inf)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def knn_search(
    state: DeviceKnnState,
    queries: jax.Array,  # [q, dim]
    k: int,
    metric: str = "cos",
) -> tuple[jax.Array, jax.Array]:
    """Top-k search. Returns (scores [q,k], slots [q,k]); empty hits have
    score ``-inf`` and slot ``capacity`` (host filters them)."""
    scores = _scores(state, queries, metric)
    top_scores, top_idx = lax.top_k(scores, k)
    capacity = state.vectors.shape[0]
    top_idx = jnp.where(jnp.isfinite(top_scores), top_idx, capacity)
    return top_scores, top_idx


def knn_search_sharded(
    state: DeviceKnnState,
    queries: jax.Array,
    k: int,
    mesh: Mesh,
    metric: str = "cos",
) -> tuple[jax.Array, jax.Array]:
    """Sharded search: local top-k per capacity shard, then a merge top-k.

    Avoids materialising the global ``[q, capacity]`` score matrix across
    devices — only ``[q, k]`` candidates ride the ICI all-gather.
    """
    n = mesh_axis_size(mesh, DATA_AXIS)
    if n <= 1:
        return knn_search(state, queries, k, metric)
    cap_local = state.vectors.shape[0] // n
    # Per-shard candidate count can't exceed the shard's capacity.
    k_local = min(k, cap_local)

    def local(state_l: DeviceKnnState, q: jax.Array):
        scores = _scores(state_l, q, metric)
        s, i = lax.top_k(scores, k_local)
        shard = lax.axis_index(DATA_AXIS)
        i = i + shard * cap_local  # globalize slot ids
        s_all = lax.all_gather(s, DATA_AXIS, axis=1, tiled=True)
        i_all = lax.all_gather(i, DATA_AXIS, axis=1, tiled=True)
        ms, mi = lax.top_k(s_all, k)
        sel = jnp.take_along_axis(i_all, mi, axis=1)
        sel = jnp.where(jnp.isfinite(ms), sel, cap_local * n)
        return ms, sel

    spec_state = DeviceKnnState(
        vectors=P(DATA_AXIS, None), valid=P(DATA_AXIS), norms=P(DATA_AXIS)
    )
    from pathway_tpu.parallel.sharding import shard_map_norep

    fn = shard_map_norep(
        local,
        mesh=mesh,
        in_specs=(spec_state, P()),
        out_specs=(P(), P()),
    )
    return fn(state, queries)
