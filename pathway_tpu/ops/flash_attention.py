"""Pallas TPU flash attention — fused attention for the encoder/ViT
``attn_fn`` seam (models/transformer.py encoder_forward and
models/vision.py vision_forward both accept any AttnFn; the causal GQA
decoder keeps its own cache-aware attention).

Why a kernel: dense attention materializes the ``[t, t]`` score matrix in
HBM per (batch, head); at long context that matrix dominates bandwidth.
Flash attention streams K/V tiles through VMEM with an online softmax, so
HBM traffic stays O(t·d) (the How-to-Scale-Your-Model recipe; same
algorithm as Dao et al.'s FlashAttention, laid out for the MXU/VPU).

Shape contract matches ``dense_attention``: q/k/v ``[b, t, h, d]``, mask
``[b, t]`` bool (True = real token) or None -> ``[b, t, h, d]``.

Details:
- grid is one program per (batch·head, q tile); K/V ride whole-sequence
  VMEM blocks and the inner loop walks K in ``block_k`` steps.
- the padding bias stays ``[b, 1, t]`` — the index map folds head into
  batch (``bh // h``), so the h-fold broadcast never materializes.
- sequences that don't divide the 128 tile are padded with masked keys /
  zero queries and sliced back (model paths bucket to powers of two, so
  padding is the exception, not the rule).
- f32 accumulators; inputs may be bf16.
- differentiable: ``jax.custom_vjp`` with a TILED backward — the
  forward also emits the per-row logsumexp, and two Pallas kernels
  recompute probabilities tile-by-tile (dQ over q tiles, dK/dV over k
  tiles, the standard flash backward split), so the backward's HBM
  traffic stays O(t·d) like the forward's.
- off-accelerator (CPU tests, virtual meshes) the kernels run in Pallas
  interpret mode; on the TPU backends ("tpu", and this environment's
  "axon" remote plugin) they compile.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30
_BLOCK = 128


def _flash_kernel(
    q_ref,  # [1, block_q, d]
    k_ref,  # [1, t, d]
    v_ref,  # [1, t, d]
    bias_ref,  # [1, 1, t]  additive mask (0 or -inf)
    o_ref,  # [1, block_q, d]
    lse_ref,  # [1, block_q]  per-row logsumexp (backward residual)
    *,
    block_k: int,
    scale: float,
):
    t = k_ref.shape[1]
    _one, block_q, d = q_ref.shape
    q = q_ref[0].astype(jnp.float32) * scale

    def body(start, carry):
        acc, m_prev, l_prev = carry
        k_tile = k_ref[0, pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        v_tile = v_ref[0, pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        bias = bias_ref[0, 0, pl.dslice(start * block_k, block_k)].astype(
            jnp.float32
        )
        s = q @ k_tile.T + bias[None, :]  # [block_q, block_k]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + p @ v_tile
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, t // block_k, body, (acc0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


@functools.partial(jax.jit, static_argnames=("h", "interpret"))
def _flash_bhtd(
    q: jax.Array,  # [bh, t, d]
    k: jax.Array,
    v: jax.Array,
    bias: jax.Array,  # [b, 1, t] — heads fold via the index map
    h: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    bh, t, d = q.shape
    block_q = min(t, _BLOCK)
    block_k = min(t, _BLOCK)
    scale = 1.0 / math.sqrt(d)
    grid = (bh, t // block_q)
    return pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i, h=h: (b // h, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ),
        interpret=interpret,
    )(q, k, v, bias)


def _flash_bwd_dq_kernel(
    q_ref,  # [1, block_q, d]
    k_ref,  # [1, t, d]
    v_ref,  # [1, t, d]
    bias_ref,  # [1, 1, t]
    do_ref,  # [1, block_q, d]
    lse_ref,  # [1, block_q]
    delta_ref,  # [1, block_q]  rowsum(dO * O)
    dq_ref,  # [1, block_q, d]
    *,
    block_k: int,
    scale: float,
):
    t = k_ref.shape[1]
    _one, block_q, d = q_ref.shape
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]

    def body(start, acc):
        k_tile = k_ref[0, pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        v_tile = v_ref[0, pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        bias = bias_ref[0, 0, pl.dslice(start * block_k, block_k)].astype(
            jnp.float32
        )
        s = q @ k_tile.T + bias[None, :]
        p = jnp.exp(s - lse[:, None])  # true softmax probs via saved lse
        dp = do @ v_tile.T  # [block_q, block_k]
        ds = p * (dp - delta[:, None])
        return acc + ds @ k_tile

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    acc = jax.lax.fori_loop(0, t // block_k, body, acc0)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref,  # [1, t, d]
    k_ref,  # [1, block_k, d]
    v_ref,  # [1, block_k, d]
    bias_ref,  # [1, 1, block_k]
    do_ref,  # [1, t, d]
    lse_ref,  # [1, t]
    delta_ref,  # [1, t]
    dk_ref,  # [1, block_k, d]
    dv_ref,  # [1, block_k, d]
    dbias_ref,  # [1, block_k]  sum of dS over heads' rows (this bh slice)
    *,
    block_q: int,
    scale: float,
):
    t = q_ref.shape[1]
    _one, block_k, d = k_ref.shape
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bias = bias_ref[0, 0].astype(jnp.float32)

    def body(start, carry):
        dk_acc, dv_acc, db_acc = carry
        q_tile = q_ref[0, pl.dslice(start * block_q, block_q), :].astype(
            jnp.float32
        ) * scale
        do_tile = do_ref[0, pl.dslice(start * block_q, block_q), :].astype(
            jnp.float32
        )
        lse = lse_ref[0, pl.dslice(start * block_q, block_q)]
        delta = delta_ref[0, pl.dslice(start * block_q, block_q)]
        s = q_tile @ k.T + bias[None, :]  # [block_q, block_k]
        p = jnp.exp(s - lse[:, None])
        dv_acc = dv_acc + p.T @ do_tile
        dp = do_tile @ v.T
        ds = p * (dp - delta[:, None])
        dk_acc = dk_acc + ds.T @ q_tile  # q_tile already carries scale
        db_acc = db_acc + ds.sum(axis=0)  # bias enters s unscaled
        return dk_acc, dv_acc, db_acc

    zeros = jnp.zeros((block_k, d), jnp.float32)
    db0 = jnp.zeros((block_k,), jnp.float32)
    dk, dv, db = jax.lax.fori_loop(
        0, t // block_q, body, (zeros, zeros, db0)
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    dbias_ref[0] = db


@functools.partial(jax.jit, static_argnames=("h", "interpret"))
def _flash_bwd_bhtd(
    q: jax.Array,  # [bh, t, d]
    k: jax.Array,
    v: jax.Array,
    bias: jax.Array,  # [b, 1, t]
    do: jax.Array,  # [bh, t, d]
    lse: jax.Array,  # [bh, t]
    delta: jax.Array,  # [bh, t]
    h: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    bh, t, d = q.shape
    block = min(t, _BLOCK)
    scale = 1.0 / math.sqrt(d)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, t // block),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i, h=h: (b // h, 0, 0)),
            pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block), lambda b, i: (b, i)),
            pl.BlockSpec((1, block), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, bias, do, lse, delta)
    dk, dv, dbias = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block, scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ),
        grid=(bh, t // block),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block), lambda b, j, h=h: (b // h, 0, j)),
            pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, t), lambda b, j: (b, 0)),
            pl.BlockSpec((1, t), lambda b, j: (b, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block), lambda b, j: (b, j)),
        ),
        interpret=interpret,
    )(q, k, v, bias, do, lse, delta)
    return dq, dk, dv, dbias


def _interpret() -> bool:
    # pallas compiles on real TPU backends; "axon" is this environment's
    # remote-TPU plugin (PALLAS_AXON_REMOTE_COMPILE). Anything else
    # (cpu tests, virtual meshes) interprets.
    return jax.default_backend() not in ("tpu", "axon")


def _pad_t(x, pad, fill=0.0):
    if not pad:
        return x
    shape = (x.shape[0], pad) + x.shape[2:]
    return jnp.concatenate([x, jnp.full(shape, fill, x.dtype)], axis=1)


def _prepare(q, k, v, bias):
    """Pad to the tile size and fold [b,t,h,d] -> [b*h,t,d]."""
    b, t, h, d = q.shape
    block = min(t, _BLOCK)
    pad = (-t) % block
    # tail tile: masked keys contribute -inf bias; extra query rows
    # compute garbage that is sliced away on exit
    q, k, v = _pad_t(q, pad), _pad_t(k, pad), _pad_t(v, pad)
    bias = _pad_t(bias, pad, fill=_NEG_INF)

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    return to_bhtd(q), to_bhtd(k), to_bhtd(v), bias, pad


def _from_bhtd(x, b, h, t):
    tt = x.shape[1]
    out = x.reshape(b, h, tt, -1).transpose(0, 2, 1, 3)
    return out[:, :t] if tt != t else out


@jax.custom_vjp
def _flash_diff(q, k, v, bias):
    return _flash_diff_fwd(q, k, v, bias)[0]


def _flash_diff_fwd(q, k, v, bias):
    b, t, h, _d = q.shape
    interpret = _interpret()
    qb, kb, vb, bias_p, pad = _prepare(q, k, v, bias)
    out_b, lse = _flash_bhtd(
        qb, kb, vb, bias_p[:, None, :], h, interpret=interpret
    )
    out = _from_bhtd(out_b, b, h, t)
    res = (qb, kb, vb, bias_p, out_b, lse, (b, t, h, pad, interpret))
    return out, res


def _flash_diff_bwd(res, g):
    qb, kb, vb, bias_p, out_b, lse, (b, t, h, pad, interpret) = res
    d = qb.shape[-1]
    g = _pad_t(g, pad)
    do = g.transpose(0, 2, 1, 3).reshape(b * h, g.shape[1], d)
    delta = (do.astype(jnp.float32) * out_b.astype(jnp.float32)).sum(-1)
    dq, dk, dv, dbias_bh = _flash_bwd_bhtd(
        qb, kb, vb, bias_p[:, None, :], do, lse, delta, h,
        interpret=interpret,
    )
    tt = qb.shape[1]
    dbias = dbias_bh.reshape(b, h, tt).sum(axis=1)[:, :t]
    return (
        _from_bhtd(dq, b, h, t),
        _from_bhtd(dk, b, h, t),
        _from_bhtd(dv, b, h, t),
        dbias.astype(jnp.float32),
    )


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(
    q: jax.Array,  # [b, t, h, d]
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None,  # [b, t] bool
) -> jax.Array:
    """Drop-in ``AttnFn`` (models/transformer.py dense_attention
    contract), differentiable end to end (tiled flash backward)."""
    b, t = q.shape[:2]
    if mask is None:
        bias = jnp.zeros((b, t), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG_INF).astype(jnp.float32)
    return _flash_diff(q, k, v, bias)
