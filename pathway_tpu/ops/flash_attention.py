"""Pallas TPU flash attention — fused attention for the encoder/ViT
``attn_fn`` seam (models/transformer.py encoder_forward and
models/vision.py vision_forward both accept any AttnFn; the causal GQA
decoder keeps its own cache-aware attention).

Why a kernel: dense attention materializes the ``[t, t]`` score matrix in
HBM per (batch, head); at long context that matrix dominates bandwidth.
Flash attention streams K/V tiles through VMEM with an online softmax, so
HBM traffic stays O(t·d) (the How-to-Scale-Your-Model recipe; same
algorithm as Dao et al.'s FlashAttention, laid out for the MXU/VPU).

Shape contract matches ``dense_attention``: q/k/v ``[b, t, h, d]``, mask
``[b, t]`` bool (True = real token) or None -> ``[b, t, h, d]``.

Details:
- grid is one program per (batch·head, q tile); K/V ride whole-sequence
  VMEM blocks and the inner loop walks K in ``block_k`` steps.
- the padding bias stays ``[b, 1, t]`` — the index map folds head into
  batch (``bh // h``), so the h-fold broadcast never materializes.
- sequences that don't divide the 128 tile are padded with masked keys /
  zero queries and sliced back (model paths bucket to powers of two, so
  padding is the exception, not the rule).
- f32 accumulators; inputs may be bf16.
- differentiable: ``jax.custom_vjp`` with a dense-recompute backward
  (the O(t^2) backward of the reference math — a flash backward kernel
  is future work), so the kernel drops into the training seam too.
- off-accelerator (CPU tests, virtual meshes) the kernel runs in Pallas
  interpret mode; on the TPU backends ("tpu", and this environment's
  "axon" remote plugin) it compiles.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30
_BLOCK = 128


def _flash_kernel(
    q_ref,  # [1, block_q, d]
    k_ref,  # [1, t, d]
    v_ref,  # [1, t, d]
    bias_ref,  # [1, 1, t]  additive mask (0 or -inf)
    o_ref,  # [1, block_q, d]
    *,
    block_k: int,
    scale: float,
):
    t = k_ref.shape[1]
    _one, block_q, d = q_ref.shape
    q = q_ref[0].astype(jnp.float32) * scale

    def body(start, carry):
        acc, m_prev, l_prev = carry
        k_tile = k_ref[0, pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        v_tile = v_ref[0, pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        bias = bias_ref[0, 0, pl.dslice(start * block_k, block_k)].astype(
            jnp.float32
        )
        s = q @ k_tile.T + bias[None, :]  # [block_q, block_k]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + p.sum(axis=-1)
        acc = acc * alpha[:, None] + p @ v_tile
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, _m, l = jax.lax.fori_loop(0, t // block_k, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("h", "interpret"))
def _flash_bhtd(
    q: jax.Array,  # [bh, t, d]
    k: jax.Array,
    v: jax.Array,
    bias: jax.Array,  # [b, 1, t] — heads fold via the index map
    h: int,
    interpret: bool = False,
) -> jax.Array:
    bh, t, d = q.shape
    block_q = min(t, _BLOCK)
    block_k = min(t, _BLOCK)
    scale = 1.0 / math.sqrt(d)
    grid = (bh, t // block_q)
    return pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i, h=h: (b // h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, bias)


def _forward(q, k, v, bias):
    """q/k/v [b, t, h, d], bias [b, t] additive -> [b, t, h, d]."""
    b, t, h, d = q.shape
    # pallas compiles on real TPU backends; "axon" is this environment's
    # remote-TPU plugin (PALLAS_AXON_REMOTE_COMPILE). Anything else
    # (cpu tests, virtual meshes) interprets.
    interpret = jax.default_backend() not in ("tpu", "axon")
    block = min(t, _BLOCK)
    pad = (-t) % block
    if pad:
        # tail tile: masked keys contribute -inf bias; extra query rows
        # compute garbage that is sliced away below
        zeros = lambda x: jnp.zeros(  # noqa: E731
            (b, pad) + x.shape[2:], x.dtype
        )
        q = jnp.concatenate([q, zeros(q)], axis=1)
        k = jnp.concatenate([k, zeros(k)], axis=1)
        v = jnp.concatenate([v, zeros(v)], axis=1)
        bias = jnp.concatenate(
            [bias, jnp.full((b, pad), _NEG_INF, bias.dtype)], axis=1
        )

    def to_bhtd(x):
        tt = x.shape[1]
        return x.transpose(0, 2, 1, 3).reshape(b * h, tt, d)

    out = _flash_bhtd(
        to_bhtd(q), to_bhtd(k), to_bhtd(v), bias[:, None, :], h,
        interpret=interpret,
    )
    tt = out.shape[1]
    out = out.reshape(b, h, tt, d).transpose(0, 2, 1, 3)
    return out[:, :t] if pad else out


@jax.custom_vjp
def _flash_diff(q, k, v, bias):
    return _forward(q, k, v, bias)


def _flash_diff_fwd(q, k, v, bias):
    return _forward(q, k, v, bias), (q, k, v, bias)


def _flash_diff_bwd(res, g):
    # dense-recompute backward: exact gradients via the reference math
    # (O(t^2) memory for the backward only; a flash backward kernel is
    # the round-4 item)
    q, k, v, bias = res

    def dense(q_, k_, v_, bias_):
        d = q_.shape[-1]
        s = jnp.einsum("bthd,bshd->bhts", q_, k_).astype(
            jnp.float32
        ) / math.sqrt(d)
        s = s + bias_[:, None, None, :]
        p = jax.nn.softmax(s, axis=-1).astype(v_.dtype)
        return jnp.einsum("bhts,bshd->bthd", p, v_)

    _out, vjp = jax.vjp(dense, q, k, v, bias)
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(
    q: jax.Array,  # [b, t, h, d]
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None,  # [b, t] bool
) -> jax.Array:
    """Drop-in ``AttnFn`` (models/transformer.py dense_attention
    contract), differentiable (dense-recompute backward)."""
    b, t = q.shape[:2]
    if mask is None:
        bias = jnp.zeros((b, t), jnp.float32)
    else:
        bias = jnp.where(mask, 0.0, _NEG_INF).astype(jnp.float32)
    return _flash_diff(q, k, v, bias)
