"""Pass 1 — dtype/schema propagation over the engine Node DAG.

Infers a set of possible :class:`~pathway_tpu.engine.value.Type` members for
every column of every node, walking ``scope.nodes`` in construction order
(inputs always precede their consumers, so the list is already
topologically sorted).  Column types are ``frozenset[Type]``:

- ``{Type.ANY}`` — unknown / opaque (the analysis stays silent);
- ``Type.NONE`` as a member — the column is optional;
- a concrete set with no valid interpretation for an operation —
  a finding, because at runtime the same row would poison to ``Error``.

Soundness rule: a finding is only emitted when the contradiction is
*provable*, i.e. every concrete interpretation of the operand types fails.
``ANY`` anywhere suppresses the check.  This keeps the pass silent on
graphs built without schema hints while still catching the classic
runtime-``Error`` sources (string minus int, join on disjoint key dtypes,
sum over tuples, flatten over scalars).
"""

from __future__ import annotations

from typing import Sequence

from pathway_tpu.analysis.findings import Finding, Report, Severity
from pathway_tpu.engine import expression as ex
from pathway_tpu.engine import graph as g
from pathway_tpu.engine.reducers import ReducerKind
from pathway_tpu.engine.value import Type, value_type_of

TS = frozenset  # frozenset[Type]

ANY: TS = frozenset({Type.ANY})
BOOL: TS = frozenset({Type.BOOL})
INT: TS = frozenset({Type.INT})
TUPLE: TS = frozenset({Type.TUPLE})
POINTER: TS = frozenset({Type.POINTER})

_NUMERIC = {Type.BOOL, Type.INT, Type.FLOAT}
_INTISH = {Type.BOOL, Type.INT}
_SEQ = {Type.TUPLE, Type.LIST}
_DATES = {Type.DATE_TIME_NAIVE, Type.DATE_TIME_UTC}
#: value kinds a FlattenNode can expand via list(value)
_FLATTENABLE = {
    Type.TUPLE,
    Type.LIST,
    Type.ARRAY,
    Type.STRING,
    Type.BYTES,
    Type.JSON,
}
#: value kinds SUM-style numeric reducers accept
_SUMMABLE = {
    Type.BOOL,
    Type.INT,
    Type.FLOAT,
    Type.DURATION,
    Type.ARRAY,
    Type.STRING,  # str concatenation via + still works in this engine
    Type.BYTES,
    Type.TUPLE,  # tuple concatenation
    Type.LIST,
}


def _num2(lt: Type, rt: Type, float_result: bool = False) -> Type | None:
    if lt in _NUMERIC and rt in _NUMERIC:
        if float_result or Type.FLOAT in (lt, rt):
            return Type.FLOAT
        return Type.INT
    return None


def _binary_result(op: str, lt: Type, rt: Type) -> Type | None:
    """Result type of ``lt op rt`` for concrete operand types, or None when
    the pair is invalid.  Mirrors the runtime semantics of
    ``expression._BINARY_OPS`` (plain Python operators + numpy ``@``)."""
    arr = Type.ARRAY
    if op in ("==", "!="):
        return Type.BOOL
    if op in ("<", "<=", ">", ">="):
        if arr in (lt, rt) and (lt == rt or lt in _NUMERIC or rt in _NUMERIC):
            return arr  # elementwise comparison
        if lt in _NUMERIC and rt in _NUMERIC:
            return Type.BOOL
        if lt == rt and lt in (
            Type.STRING,
            Type.BYTES,
            Type.DURATION,
            Type.POINTER,
            Type.DATE_TIME_NAIVE,
            Type.DATE_TIME_UTC,
            Type.TUPLE,
            Type.LIST,
        ):
            return Type.BOOL
        if lt in _SEQ and rt in _SEQ:
            return Type.BOOL
        return None
    if arr in (lt, rt) and op != "@":
        # numpy broadcasts arrays against numbers and other arrays
        if lt == rt or lt in _NUMERIC or rt in _NUMERIC:
            return arr
        return None
    if op == "+":
        n = _num2(lt, rt)
        if n is not None:
            return n
        if lt == rt and lt in (Type.STRING, Type.BYTES, Type.DURATION):
            return lt
        if lt in _SEQ and rt in _SEQ:
            return Type.TUPLE
        if lt in _DATES and rt == Type.DURATION:
            return lt
        if lt == Type.DURATION and rt in _DATES:
            return rt
        return None
    if op == "-":
        n = _num2(lt, rt)
        if n is not None:
            return n
        if lt == rt and lt == Type.DURATION:
            return Type.DURATION
        if lt in _DATES and rt == lt:
            return Type.DURATION
        if lt in _DATES and rt == Type.DURATION:
            return lt
        return None
    if op == "*":
        n = _num2(lt, rt)
        if n is not None:
            return n
        for a, b in ((lt, rt), (rt, lt)):
            if b in _INTISH:
                if a in (Type.STRING, Type.BYTES):
                    return a
                if a in _SEQ:
                    return Type.TUPLE
            if a == Type.DURATION and b in _NUMERIC:
                return Type.DURATION
        return None
    if op == "/":
        if lt in _NUMERIC and rt in _NUMERIC:
            return Type.FLOAT
        if lt == Type.DURATION and rt in _NUMERIC:
            return Type.DURATION
        if lt == Type.DURATION and rt == Type.DURATION:
            return Type.FLOAT
        return None
    if op == "//":
        n = _num2(lt, rt)
        if n is not None:
            return n
        if lt == Type.DURATION and rt == Type.DURATION:
            return Type.INT
        if lt == Type.DURATION and rt in _NUMERIC:
            return Type.DURATION
        return None
    if op == "%":
        if lt == Type.STRING:
            return Type.STRING  # printf-style formatting
        n = _num2(lt, rt)
        if n is not None:
            return n
        if lt == Type.DURATION and rt == Type.DURATION:
            return Type.DURATION
        return None
    if op == "**":
        return _num2(lt, rt)
    if op in ("&", "|", "^"):
        if lt in _INTISH and rt in _INTISH:
            return Type.BOOL if lt == rt == Type.BOOL else Type.INT
        return None
    if op in ("<<", ">>"):
        if lt in _INTISH and rt in _INTISH:
            return Type.INT
        return None
    if op == "@":
        if lt == arr and rt == arr:
            return arr
        return None
    return None


def _unary_result(op: str, t: Type) -> Type | None:
    if op == "not":
        return Type.BOOL
    if op in ("-", "abs"):
        if t in _NUMERIC:
            return Type.INT if t in _INTISH else Type.FLOAT
        if t in (Type.DURATION, Type.ARRAY):
            return t
        return None
    if op == "~":
        if t in _INTISH:
            return Type.INT
        if t == Type.ARRAY:
            return t
        return None
    return None


class _ExprTyper:
    """Types one EngineExpression tree against its input column types."""

    def __init__(self, pass_: "_DtypePass", node: g.Node, in_cols: list[TS]):
        self.pass_ = pass_
        self.node = node
        self.in_cols = in_cols

    def report(self, message: str, column: int | None = None) -> None:
        self.pass_.report(
            "PWA001", self.node, message, column=column
        )

    def infer(self, expr: ex.EngineExpression) -> TS:
        if isinstance(expr, ex.ColumnRef):
            if 0 <= expr.index < len(self.in_cols):
                return self.in_cols[expr.index]
            self.report(
                f"column reference col[{expr.index}] is out of range "
                f"(input has {len(self.in_cols)} columns)"
            )
            return ANY
        if isinstance(expr, ex.KeyRef):
            return POINTER
        if isinstance(expr, ex.Const):
            return frozenset({value_type_of(expr.value)})
        if isinstance(expr, ex.Binary):
            return self._binary(expr)
        if isinstance(expr, ex.Unary):
            return self._unary(expr)
        if isinstance(expr, ex.BooleanChain):
            for arg in expr.args:
                self.infer(arg)
            return BOOL
        if isinstance(expr, ex.IfElse):
            cond = self.infer(expr.cond)
            if cond == frozenset({Type.NONE}):
                self.report("if_else condition is always None")
            return self.infer(expr.then) | self.infer(expr.otherwise)
        if isinstance(expr, ex.IsNone):
            self.infer(expr.arg)
            return BOOL
        if isinstance(expr, ex.Coalesce):
            out: set[Type] = set()
            all_optional = True
            for arg in expr.args:
                ts = self.infer(arg)
                out |= set(ts) - {Type.NONE}
                if Type.NONE not in ts and Type.ANY not in ts:
                    all_optional = False
                    break  # later args are never reached
            if all_optional:
                out.add(Type.NONE)
            return frozenset(out) if out else frozenset({Type.NONE})
        if isinstance(expr, ex.Require):
            for dep in expr.deps:
                self.infer(dep)
            return self.infer(expr.value) | {Type.NONE}
        if isinstance(expr, ex.MakeTuple):
            for arg in expr.args:
                self.infer(arg)
            return TUPLE
        if isinstance(expr, ex.SequenceGet):
            seq = self.infer(expr.arg)
            self.infer(expr.index)
            if expr.default is not None:
                self.infer(expr.default)
            concrete = set(seq) - {Type.NONE}
            indexable = _FLATTENABLE | {Type.ANY}
            if concrete and not (concrete & indexable):
                self.report(
                    "sequence get over a value that is never a sequence "
                    f"(type is {_fmt(seq)})"
                )
            return ANY
        if isinstance(expr, ex.JsonGet):
            self.infer(expr.arg)
            self.infer(expr.index)
            if expr.default is not None:
                self.infer(expr.default)
            return frozenset({Type.JSON, Type.NONE, Type.ANY})
        if isinstance(expr, ex.Cast):
            return self._cast(expr)
        if isinstance(expr, ex.Convert):
            target = {
                "Int": Type.INT,
                "Float": Type.FLOAT,
                "Bool": Type.BOOL,
                "String": Type.STRING,
                "List": Type.TUPLE,
            }.get(expr.target, Type.ANY)
            self.infer(expr.arg)
            return frozenset({target, Type.NONE})
        if isinstance(expr, ex.Unwrap):
            ts = self.infer(expr.arg)
            if ts == frozenset({Type.NONE}):
                self.report("unwrap() over an always-None value")
                return ANY
            out = set(ts) - {Type.NONE}
            return frozenset(out) if out else ANY
        if isinstance(expr, ex.FillError):
            return self.infer(expr.arg) | self.infer(expr.fallback)
        if isinstance(expr, ex.Apply):
            for arg in expr.args:
                self.infer(arg)
            return _apply_return_type(expr.fn)
        if isinstance(expr, ex.PointerFrom):
            for arg in expr.args:
                self.infer(arg)
            if expr.instance is not None:
                self.infer(expr.instance)
            return POINTER
        return ANY  # unknown expression kind: stay silent

    def _operand(self, ts: TS, op: str, side: str) -> set[Type] | None:
        """Concrete operand members for a binary/unary op; None = skip the
        check (ANY present, or the operand is runtime-guarded None)."""
        if Type.ANY in ts:
            return None
        concrete = set(ts) - {Type.NONE}
        if not concrete:
            if op not in ex._NONE_SAFE_OPS:
                self.report(
                    f"{side} operand of {op!r} is always None "
                    "(the runtime reports every such row as Error)"
                )
            return None
        return concrete

    def _binary(self, expr: ex.Binary) -> TS:
        lts = self.infer(expr.left)
        rts = self.infer(expr.right)
        left = self._operand(lts, expr.op, "left")
        right = self._operand(rts, expr.op, "right")
        if left is None or right is None:
            return ANY
        results = {
            r
            for lt in left
            for rt in right
            if (r := _binary_result(expr.op, lt, rt)) is not None
        }
        if not results:
            self.report(
                f"operator {expr.op!r} can never apply to operand types "
                f"{_fmt(lts)} and {_fmt(rts)}"
            )
            return ANY
        return frozenset(results)

    def _unary(self, expr: ex.Unary) -> TS:
        ts = self.infer(expr.arg)
        operand = self._operand(ts, expr.op, "the")
        if operand is None:
            return ANY
        results = {
            r for t in operand if (r := _unary_result(expr.op, t)) is not None
        }
        if not results:
            self.report(
                f"unary {expr.op!r} can never apply to type {_fmt(ts)}"
            )
            return ANY
        return frozenset(results)

    def _cast(self, expr: ex.Cast) -> TS:
        ts = self.infer(expr.arg)
        target = {
            "Int": Type.INT,
            "Float": Type.FLOAT,
            "Bool": Type.BOOL,
            "String": Type.STRING,
        }.get(expr.target, Type.ANY)
        castable = {
            # int()/float() accept numbers and numeric strings; bool() and
            # str() accept anything
            Type.INT: _NUMERIC | {Type.STRING},
            Type.FLOAT: _NUMERIC | {Type.STRING},
        }.get(target)
        concrete = set(ts) - {Type.NONE}
        if (
            castable is not None
            and concrete
            and Type.ANY not in concrete
            and not (concrete & castable)
        ):
            self.pass_.report(
                "PWA008",
                self.node,
                f"cast to {expr.target} from type {_fmt(ts)} can never "
                "succeed",
                severity=Severity.WARNING,
            )
        out = {target}
        if Type.NONE in ts or Type.ANY in ts:
            out.add(Type.NONE)  # Cast passes None through
        return frozenset(out)


def _apply_return_type(fn) -> TS:
    """Map a UDF's return annotation to an engine type when obvious."""
    simple = {
        "int": Type.INT,
        "float": Type.FLOAT,
        "bool": Type.BOOL,
        "str": Type.STRING,
        "bytes": Type.BYTES,
        "tuple": Type.TUPLE,
        "list": Type.LIST,
    }
    try:
        ann = getattr(fn, "__annotations__", {}).get("return")
    except Exception:  # noqa: BLE001
        return ANY
    if ann is None:
        return ANY
    name = ann if isinstance(ann, str) else getattr(ann, "__name__", None)
    t = simple.get(name)
    return frozenset({t}) if t is not None else ANY


def _fmt(ts: TS) -> str:
    names = sorted(t.name for t in ts)
    return names[0] if len(names) == 1 else "{" + "|".join(names) + "}"


def _comparable(lts: TS, rts: TS) -> bool:
    """Can values of these types ever compare equal (join keys)?"""
    lc = set(lts) - {Type.NONE}
    rc = set(rts) - {Type.NONE}
    if not lc or not rc or Type.ANY in lc or Type.ANY in rc:
        return True
    # numeric cross-equality (1 == 1.0 == True) and Pointer-as-int
    groups = [_NUMERIC | {Type.POINTER}, _SEQ]
    for gset in groups:
        if lc & gset and rc & gset:
            return True
    return bool(lc & rc)


class _DtypePass:
    def __init__(self, scope: g.Scope, report: Report) -> None:
        self.scope = scope
        self.out = report
        #: node index -> output column types
        self.types: dict[int, list[TS]] = {}

    def report(
        self,
        code: str,
        node: g.Node,
        message: str,
        *,
        column: int | None = None,
        severity: Severity | None = None,
    ) -> None:
        from pathway_tpu.analysis.findings import FINDING_CODES

        self.out.add(
            Finding(
                code=code,
                message=message,
                node_index=node.index,
                node_name=node.name,
                severity=severity or FINDING_CODES[code][0],
                column=column,
                trace=getattr(node, "trace", None) or None,
            )
        )

    def run(self) -> dict[int, list[TS]]:
        for node in self.scope.nodes:
            try:
                cols = self._infer_node(node)
            except Exception:  # noqa: BLE001 — one bad node must not
                cols = None  # silence the whole pass; fall through to ANY
            if cols is None:
                cols = [ANY] * node.arity
            # robustness: never let a transfer-function bug corrupt widths
            if len(cols) < node.arity:
                cols = cols + [ANY] * (node.arity - len(cols))
            elif len(cols) > node.arity:
                cols = cols[: node.arity]
            self.types[node.index] = cols
        return self.types

    def _in(self, node: g.Node, port: int = 0) -> list[TS]:
        src = node.inputs[port]
        return self.types.get(src.index, [ANY] * src.arity)

    def _declared(self, node: g.Node) -> list[TS] | None:
        """Schema hint attached by the framework runner (internals/runner.py
        sets ``node.schema_types`` from the Table dtypes)."""
        hint = getattr(node, "schema_types", None)
        if hint is None or len(hint) != node.arity:
            return None
        return [frozenset(ts) for ts in hint]

    # -- per-node transfer functions ---------------------------------------

    def _infer_node(self, node: g.Node) -> list[TS] | None:
        from pathway_tpu.engine import temporal as t
        from pathway_tpu.engine.iterate import IterateNode

        if isinstance(node, g.StaticSource):
            return self._static_source(node)
        if isinstance(node, g.InputSession):
            return self._declared(node) or [ANY] * node.arity
        if isinstance(node, g.ExpressionNode):
            typer = _ExprTyper(self, node, self._in(node))
            return [typer.infer(e) for e in node.expressions]
        if isinstance(node, g.BatchApplyNode):
            return self._declared(node) or [_apply_return_type(node.rows_fn)]
        if isinstance(node, g.FilterNode):
            return self._filter(node)
        if isinstance(node, g.ConcatNode):
            return self._concat(node)
        if isinstance(node, g.ReindexNode):
            self._require_pointer(node, self._in(node), node.key_col)
            return self._in(node)
        if isinstance(node, (g.KeyFilterNode, g.OverrideUniverseNode)):
            return self._in(node)
        if isinstance(node, g._RemoveErrorsNode):
            return self._in(node)
        if isinstance(node, g.ZipNode):
            out: list[TS] = []
            for port in range(len(node.inputs)):
                out.extend(self._in(node, port))
            return out
        if isinstance(node, g.JoinNode):
            return self._join(node)
        if isinstance(node, g.GroupbyNode):
            return self._groupby(node)
        if isinstance(node, g.DeduplicateNode):
            return self._in(node)
        if isinstance(node, g.FlattenNode):
            return self._flatten(node)
        if isinstance(node, g.SortNode):
            opt_ptr = frozenset({Type.POINTER, Type.NONE})
            return [opt_ptr, opt_ptr]
        if isinstance(node, g.IxNode):
            return self._ix(node)
        if isinstance(node, g.UpdateRowsNode):
            a, b = self._in(node, 0), self._in(node, 1)
            return [x | y for x, y in zip(a, b)]
        if isinstance(node, g.UpdateCellsNode):
            orig, upd = self._in(node, 0), self._in(node, 1)
            out = []
            for i, uc in enumerate(node.update_cols):
                base = orig[i] if i < len(orig) else ANY
                if uc >= 0 and uc < len(upd):
                    out.append(base | upd[uc])
                else:
                    out.append(base)
            return out
        if isinstance(node, g.SubscribeNode):
            return self._in(node)
        if isinstance(node, g.ErrorLogNode):
            return [frozenset({Type.STRING})]
        if isinstance(node, (g.RecomputeNode, IterateNode)):
            return self._declared(node) or [ANY] * node.arity
        if isinstance(node, (t.BufferNode, t.FreezeNode)):
            return self._in(node)
        if isinstance(node, t.ForgetNode):
            src = self._in(node)
            return src + [BOOL] if node.mark else src
        if isinstance(node, t.SessionAssignNode):
            src = self._in(node)
            time_ts = (
                src[node.time_col] if node.time_col < len(src) else ANY
            )
            return src + [time_ts, time_ts]
        if isinstance(node, (t.IntervalJoinNode, t.AsofJoinNode)):
            return self._temporal_join(node)
        if isinstance(node, t.AsofNowJoinNode):
            return self._asof_now(node)
        if isinstance(node, t.GradualBroadcastNode):
            return self._in(node) + [ANY]
        return self._declared(node)  # unknown node kind: hint or ANY

    def _static_source(self, node: g.StaticSource) -> list[TS]:
        declared = self._declared(node)
        rows = node._rows[:100]
        if not rows:
            return declared or [ANY] * node.arity
        cols: list[set[Type]] = [set() for _ in range(node.arity)]
        for _key, row in rows:
            for i in range(min(node.arity, len(row))):
                try:
                    cols[i].add(value_type_of(row[i]))
                except Exception:  # noqa: BLE001
                    cols[i].add(Type.ANY)
        sampled = [frozenset(c) if c else ANY for c in cols]
        if len(node._rows) > 100:
            # partial sample: the tail may widen any column
            sampled = [ts | {Type.ANY} for ts in sampled]
        return sampled

    def _filter(self, node: g.FilterNode) -> list[TS]:
        src = self._in(node)
        c = node.condition_col
        cond = src[c] if 0 <= c < len(src) else ANY
        if cond == frozenset({Type.NONE}):
            self.report(
                "PWA002",
                node,
                "filter condition column is always None — the output is "
                "provably empty",
                column=c,
            )
        elif Type.ANY not in cond and not (set(cond) & (_NUMERIC | {Type.NONE})):
            self.report(
                "PWA002",
                node,
                f"filter condition column has type {_fmt(cond)}, not a "
                "boolean",
                column=c,
                severity=Severity.WARNING,
            )
        return src

    def _concat(self, node: g.ConcatNode) -> list[TS]:
        ins = [self._in(node, p) for p in range(len(node.inputs))]
        out: list[TS] = []
        for i in range(node.arity):
            col_sets = [src[i] if i < len(src) else ANY for src in ins]
            merged = frozenset().union(*col_sets)
            concrete = [
                set(ts) - {Type.NONE}
                for ts in col_sets
                if Type.ANY not in ts and set(ts) - {Type.NONE}
            ]
            if len(concrete) > 1:
                base = concrete[0]
                for other in concrete[1:]:
                    if not _comparable(frozenset(base), frozenset(other)):
                        self.report(
                            "PWA007",
                            node,
                            "concat inputs disagree on the column type: "
                            + " vs ".join(
                                _fmt(frozenset(c)) for c in concrete
                            ),
                            column=i,
                        )
                        break
            out.append(merged)
        return out

    def _require_pointer(
        self, node: g.Node, src: list[TS], col: int, what: str = "key column"
    ) -> None:
        ts = src[col] if 0 <= col < len(src) else ANY
        concrete = set(ts) - {Type.NONE}
        if concrete and Type.ANY not in concrete and Type.POINTER not in concrete:
            # int keys hash like pointers in this engine, so only flag
            # types that can never act as a row id
            if not (concrete & _INTISH):
                self.report(
                    "PWA004",
                    node,
                    f"{what} has type {_fmt(ts)}; a Pointer is required",
                    column=col,
                )

    def _join(self, node: g.JoinNode) -> list[TS]:
        left, right = self._in(node, 0), self._in(node, 1)
        for lc, rc in zip(node.left_on, node.right_on):
            lts = left[lc] if lc < len(left) else ANY
            rts = right[rc] if rc < len(right) else ANY
            if not _comparable(lts, rts):
                self.report(
                    "PWA003",
                    node,
                    f"join keys can never match: left col {lc} is "
                    f"{_fmt(lts)}, right col {rc} is {_fmt(rts)}",
                )
        k = node.kind
        lcols = list(left)
        rcols = list(right)
        if k in (g.JoinKind.RIGHT, g.JoinKind.OUTER):
            lcols = [ts | {Type.NONE} for ts in lcols]
        if k in (g.JoinKind.LEFT, g.JoinKind.OUTER):
            rcols = [ts | {Type.NONE} for ts in rcols]
        return lcols + rcols

    def _groupby(self, node: g.GroupbyNode) -> list[TS]:
        src = self._in(node)
        out = [src[c] if c < len(src) else ANY for c in node.by_cols]
        for reducer, arg_cols in node.reducers:
            arg_ts = (
                src[arg_cols[0]]
                if arg_cols and arg_cols[0] < len(src)
                else ANY
            )
            kind = getattr(reducer, "kind", None)
            if kind in (ReducerKind.COUNT, ReducerKind.COUNT_DISTINCT):
                out.append(INT)
            elif kind in (ReducerKind.ARG_MIN, ReducerKind.ARG_MAX):
                out.append(POINTER)
            elif kind in (ReducerKind.SORTED_TUPLE, ReducerKind.TUPLE):
                out.append(TUPLE)
            elif kind == ReducerKind.NDARRAY:
                out.append(frozenset({Type.ARRAY}))
            elif kind == ReducerKind.SUM:
                concrete = set(arg_ts) - {Type.NONE}
                if (
                    concrete
                    and Type.ANY not in concrete
                    and not (concrete & _SUMMABLE)
                ):
                    self.report(
                        "PWA006",
                        node,
                        f"sum reducer over type {_fmt(arg_ts)} can never "
                        "be computed",
                        column=len(out),
                    )
                out.append(arg_ts)
            elif kind in (
                ReducerKind.MIN,
                ReducerKind.MAX,
                ReducerKind.ANY,
                ReducerKind.UNIQUE,
                ReducerKind.EARLIEST,
                ReducerKind.LATEST,
            ):
                out.append(arg_ts)
            else:  # STATEFUL and future kinds
                out.append(ANY)
        return out

    def _flatten(self, node: g.FlattenNode) -> list[TS]:
        src = self._in(node)
        fc = node.flat_col
        flat_ts = src[fc] if 0 <= fc < len(src) else ANY
        concrete = set(flat_ts) - {Type.NONE}
        if concrete and Type.ANY not in concrete and not (
            concrete & _FLATTENABLE
        ):
            self.report(
                "PWA005",
                node,
                f"flatten over type {_fmt(flat_ts)}, which is never a "
                "sequence",
                column=fc,
            )
        elem: TS
        if concrete <= {Type.STRING}:
            elem = frozenset({Type.STRING})
        elif concrete <= {Type.BYTES}:
            elem = INT
        else:
            elem = ANY
        out = [elem if i == fc else ts for i, ts in enumerate(src)]
        if node.with_origin:
            out.append(POINTER)
        return out

    def _ix(self, node: g.IxNode) -> list[TS]:
        keys_in = self._in(node, 0)
        source_in = self._in(node, 1)
        self._require_pointer(node, keys_in, node.key_col, "ix key column")
        if node.optional:
            return [ts | {Type.NONE} for ts in source_in]
        return list(source_in)

    def _temporal_join(self, node) -> list[TS]:
        from pathway_tpu.engine.graph import JoinKind

        left, right = self._in(node, 0), self._in(node, 1)
        lt_ts = left[node.lt] if node.lt < len(left) else ANY
        rt_ts = right[node.rt] if node.rt < len(right) else ANY
        if not _comparable(lt_ts, rt_ts):
            self.report(
                "PWA003",
                node,
                f"temporal join time columns can never align: left is "
                f"{_fmt(lt_ts)}, right is {_fmt(rt_ts)}",
            )
        lcols = list(left)
        rcols = list(right)
        if node.kind in (JoinKind.RIGHT, JoinKind.OUTER):
            lcols = [ts | {Type.NONE} for ts in lcols]
        if node.kind in (JoinKind.LEFT, JoinKind.OUTER):
            rcols = [ts | {Type.NONE} for ts in rcols]
        return lcols + rcols

    def _asof_now(self, node) -> list[TS]:
        from pathway_tpu.engine.graph import JoinKind

        left, right = self._in(node, 0), self._in(node, 1)
        for lc, rc in zip(node.left_on, node.right_on):
            lts = left[lc] if lc < len(left) else ANY
            rts = right[rc] if rc < len(right) else ANY
            if not _comparable(lts, rts):
                self.report(
                    "PWA003",
                    node,
                    f"asof_now join keys can never match: left col {lc} is "
                    f"{_fmt(lts)}, right col {rc} is {_fmt(rts)}",
                )
        rcols = list(right)
        if node.kind in (JoinKind.LEFT, JoinKind.OUTER):
            rcols = [ts | {Type.NONE} for ts in rcols]
        return list(left) + rcols


def run_pass(scope: g.Scope, report: Report) -> dict[int, list[TS]]:
    """Run dtype propagation; returns the node->column-types map (used by
    tests and future optimisation passes)."""
    return _DtypePass(scope, report).run()
