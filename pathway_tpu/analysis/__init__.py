"""Pre-execution static analysis over the engine graph.

The reference engine validates dataflow programs at graph-build time (its
``Graph`` trait carries typed column properties end to end); this package
is the equivalent floor for the TPU build: :func:`analyze_scope` walks a
built :class:`~pathway_tpu.engine.graph.Scope` *before* the scheduler
starts and returns a :class:`Report` of structured findings —

1. dtype/schema propagation (``analysis.dtypes``) — contradictions that
   would otherwise surface mid-stream as runtime ``Error`` values;
2. dead-column / unused-operator detection (``analysis.usage``) — the
   projection-pushdown report;
3. shard-preservation / exchange-redundancy analysis (``analysis.shards``);
4. UDF determinism & purity lint (``analysis.udf_lint``).

Entry points: ``pathway_tpu.cli analyze prog.py`` (human-readable report,
exit 0/1/2), ``Scope.run(strict=True)`` / ``pw.run(strict=True)`` (raise
:class:`AnalysisError` on error-severity findings), ``tools/check.py``
(pre-PR gate).
"""

from __future__ import annotations

import traceback

from pathway_tpu.analysis.findings import (  # noqa: F401 — public API
    FINDING_CODES,
    AnalysisError,
    Finding,
    Report,
    Severity,
)
from pathway_tpu.analysis.runtime import analyze_only, enabled  # noqa: F401

__all__ = [
    "FINDING_CODES",
    "AnalysisError",
    "Finding",
    "Report",
    "Severity",
    "analyze_only",
    "analyze_scope",
    "check_strict",
    "enabled",
]


def analyze_scope(scope) -> Report:
    """Run all four analyses over a built engine scope.

    A crash inside one pass is recorded in ``report.internal_errors`` (the
    CLI maps those to exit code 2) and never masks the other passes'
    findings — an analyzer bug must not look like a program bug.
    """
    from pathway_tpu.analysis import dtypes, shards, udf_lint, usage

    report = Report(node_count=len(scope.nodes))
    passes = [
        ("dtypes", dtypes.run_pass),
        ("usage", usage.run_pass),
        ("shards", shards.run_pass),
        ("udf_lint", udf_lint.run_pass),
    ]
    for name, run in passes:
        try:
            run(scope, report)
        except Exception:  # noqa: BLE001 — collected, not raised
            tail = traceback.format_exc(limit=4)
            report.internal_errors.append(f"pass {name!r} crashed: {tail}")
    return report


def check_strict(scope) -> Report:
    """Analyze and raise :class:`AnalysisError` on error-severity findings
    (the ``strict=True`` mode of ``Scope.run`` / ``pw.run``)."""
    report = analyze_scope(scope)
    if report.error_count:
        raise AnalysisError(report)
    return report
