"""Analyze-only execution mode (the machinery behind ``cli analyze``).

``pathway_tpu.cli analyze prog.py`` runs ``prog.py`` in a subprocess with
``PATHWAY_TPU_ANALYZE=1``.  In that mode the schedulers
(``Scheduler.run_static/commit/finish``, ``ShardedScheduler.commit/finish``)
call :func:`intercept` instead of executing: every scope that reaches a
scheduler is analyzed exactly once and its report appended as one JSON
line to ``PATHWAY_TPU_ANALYZE_OUT`` — the program builds its graphs
normally, but no data ever flows.
"""

from __future__ import annotations

import json
import os
import sys

#: scopes already analyzed this process; holds strong references so ids
#: cannot be recycled
_seen: list = []


def enabled() -> bool:
    """True when the process runs under ``cli analyze``."""
    return os.environ.get("PATHWAY_TPU_ANALYZE") == "1"


# the schedulers ask "should I skip execution?" — same predicate, named for
# call-site readability (bench_dataflow keys its graph-only scaling off it)
analyze_only = enabled


def record_scope(scope) -> None:
    """Analyze ``scope`` once and emit the report (JSONL file when
    ``PATHWAY_TPU_ANALYZE_OUT`` is set, stderr otherwise)."""
    if any(s is scope for s in _seen):
        return
    _seen.append(scope)
    from pathway_tpu.analysis import analyze_scope

    report = analyze_scope(scope)
    out = os.environ.get("PATHWAY_TPU_ANALYZE_OUT")
    if out:
        with open(out, "a", encoding="utf-8") as f:
            f.write(json.dumps(report.to_dict()) + "\n")
    else:
        print(report.render(), file=sys.stderr)


def intercept(scope) -> bool:
    """Scheduler gate: record + skip execution in analyze mode."""
    if not enabled():
        return False
    record_scope(scope)
    return True
