"""Structured results of the pre-execution graph analyzer.

Every analysis pass emits :class:`Finding` records tagged with a stable
``PWAxxx`` code, a severity, and node provenance (index, name, build-site
trace).  A :class:`Report` aggregates the findings for one engine
:class:`~pathway_tpu.engine.graph.Scope` plus any internal analyzer
failures — the latter are kept out of the findings list so an analyzer bug
never masquerades as a program bug (the CLI maps them to exit code 2).

Code ranges:

- ``PWA0xx`` — dtype/schema contradictions (error severity unless noted)
- ``PWA1xx`` — dead columns / unused operators
- ``PWA2xx`` — shard/exchange advisories
- ``PWA3xx`` — UDF determinism & purity lint
- ``PWC4xx`` — runtime lock-discipline lint (source-level, ``analysis.concurrency``)
- ``PWC5xx`` — scheduler/mesh protocol invariants (source-level, ``analysis.protocol``)
- ``PWD6xx`` — device-plane discipline: transfers, tracing safety,
  residency lifecycle (source-level, ``analysis.deviceplane``)

``PWC``/``PWD`` findings come from the *source tree*, not a built graph,
so their provenance fields are reinterpreted: ``node_name`` is the
relative file path and ``node_index`` the 1-based line number.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any


class Severity(str, Enum):
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"


#: code -> (default severity, short title); the README table is generated
#: from the same wording.
FINDING_CODES: dict[str, tuple[Severity, str]] = {
    "PWA001": (Severity.ERROR, "expression dtype contradiction"),
    "PWA002": (Severity.ERROR, "filter condition is provably not usable"),
    "PWA003": (Severity.ERROR, "join/temporal key dtype mismatch"),
    "PWA004": (Severity.ERROR, "key column is provably not a pointer"),
    "PWA005": (Severity.ERROR, "flatten over a provably non-sequence column"),
    "PWA006": (Severity.ERROR, "reducer argument dtype invalid"),
    "PWA007": (Severity.WARNING, "concat column dtype divergence"),
    "PWA008": (Severity.WARNING, "cast/convert can never succeed"),
    "PWA101": (Severity.WARNING, "dead column (never read downstream)"),
    "PWA102": (Severity.WARNING, "unused operator (no consumer, no sink)"),
    "PWA201": (Severity.INFO, "redundant exchange (already partitioned)"),
    "PWA202": (Severity.INFO, "operator pins the stream to worker 0"),
    "PWA301": (Severity.ERROR, "nondeterministic call in deterministic UDF"),
    "PWA302": (Severity.WARNING, "order-sensitive set iteration in UDF"),
    "PWA303": (Severity.WARNING, "UDF mutates ambient global state"),
    "PWA304": (Severity.WARNING, "caching decorator on UDF breaks replay"),
    "PWA305": (Severity.WARNING, "mutable default argument on UDF"),
    "PWC401": (Severity.ERROR, "guarded attribute written without its lock"),
    "PWC402": (Severity.ERROR, "inconsistent lock acquisition order (cycle)"),
    "PWC403": (Severity.WARNING, "blocking call while holding a lock"),
    "PWC404": (Severity.WARNING, "unbounded wait in daemon loop"),
    "PWC405": (Severity.WARNING, "guarded-by names an unknown lock"),
    "PWC501": (Severity.ERROR, "commit hook runs before device drain"),
    "PWC502": (Severity.ERROR, "rollback path cannot reach snapshot truncate"),
    "PWC503": (Severity.ERROR, "mesh frame arity drift between encode/decode"),
    "PWC504": (Severity.ERROR, "follower frame handler missing epoch fence"),
    "PWD601": (Severity.WARNING, "implicit device sync in hot path"),
    "PWD602": (Severity.ERROR, "recompile hazard: branch on traced shape/value"),
    "PWD603": (Severity.ERROR, "device transfer not counted in ledger"),
    "PWD604": (Severity.ERROR, "partial push on decline/except path"),
    "PWD605": (Severity.ERROR, "device-resident state never registered for decay"),
    "PWD606": (Severity.ERROR, "live-per-call flag cached at import scope"),
    "PWD607": (Severity.WARNING, "metric family unregistered or label drift"),
}


@dataclass
class Finding:
    code: str
    message: str
    node_index: int
    node_name: str
    severity: Severity = Severity.ERROR
    column: int | None = None
    trace: str | None = None
    #: True when a ``# pwc-ok``/``# pwd-ok`` comment waived this finding —
    #: kept out of :attr:`Report.findings` (and every count) but surfaced
    #: in ``--json`` output so CI can diff waivers, not just failures.
    waived: bool = False

    def __post_init__(self) -> None:
        assert self.code in FINDING_CODES, f"unknown finding code {self.code}"

    @property
    def title(self) -> str:
        return FINDING_CODES[self.code][1]

    def render(self) -> str:
        where = f"{self.node_name}#{self.node_index}"
        if self.column is not None:
            where += f" col {self.column}"
        line = f"{self.code} {self.severity.value:<7} {where}: {self.message}"
        if self.trace:
            line += f"  [{self.trace}]"
        return line

    def to_dict(self) -> dict[str, Any]:
        return {
            "code": self.code,
            "severity": self.severity.value,
            "message": self.message,
            "node_index": self.node_index,
            "node_name": self.node_name,
            "column": self.column,
            "trace": self.trace,
            "waived": self.waived,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Finding":
        return cls(
            code=d["code"],
            message=d["message"],
            node_index=d["node_index"],
            node_name=d["node_name"],
            severity=Severity(d["severity"]),
            column=d.get("column"),
            trace=d.get("trace"),
            waived=d.get("waived", False),
        )


_SEV_ORDER = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}


@dataclass
class Report:
    """All findings for one analyzed scope (or, in the CLI, a merge of
    every scope a program built)."""

    findings: list[Finding] = field(default_factory=list)
    #: analyzer crashes (pass name + traceback tail) — never mixed into
    #: ``findings``; any entry here means the analysis is incomplete
    internal_errors: list[str] = field(default_factory=list)
    node_count: int = 0
    #: findings suppressed by ``# pwc-ok``/``# pwd-ok`` waiver comments
    #: (``waived=True`` on each) — excluded from counts and exit codes,
    #: but emitted in machine-readable output so waivers stay auditable
    waived: list[Finding] = field(default_factory=list)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def count(self, severity: Severity) -> int:
        return sum(1 for f in self.findings if f.severity == severity)

    @property
    def error_count(self) -> int:
        return self.count(Severity.ERROR)

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == Severity.ERROR]

    def sorted_findings(self) -> list[Finding]:
        return sorted(
            self.findings,
            key=lambda f: (_SEV_ORDER[f.severity], f.node_index, f.code),
        )

    def merge(self, other: "Report") -> None:
        self.findings.extend(other.findings)
        self.internal_errors.extend(other.internal_errors)
        self.node_count += other.node_count
        self.waived.extend(other.waived)

    def render(self) -> str:
        lines = [f"analyzed {self.node_count} operator(s)"]
        for f in self.sorted_findings():
            lines.append("  " + f.render())
        for err in self.internal_errors:
            lines.append(f"  INTERNAL ANALYZER ERROR: {err}")
        lines.append(
            "summary: "
            f"{self.count(Severity.ERROR)} error(s), "
            f"{self.count(Severity.WARNING)} warning(s), "
            f"{self.count(Severity.INFO)} info"
        )
        return "\n".join(lines)

    def to_dict(self) -> dict[str, Any]:
        return {
            "node_count": self.node_count,
            "findings": [f.to_dict() for f in self.findings],
            "internal_errors": list(self.internal_errors),
            "waived": [f.to_dict() for f in self.waived],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Report":
        return cls(
            findings=[Finding.from_dict(f) for f in d.get("findings", [])],
            internal_errors=list(d.get("internal_errors", [])),
            node_count=d.get("node_count", 0),
            waived=[Finding.from_dict(f) for f in d.get("waived", [])],
        )


class AnalysisError(RuntimeError):
    """Raised by strict mode when error-severity findings exist."""

    def __init__(self, report: Report) -> None:
        self.report = report
        errors = report.errors()
        lines = [f"{len(errors)} error-severity finding(s):"]
        lines += ["  " + f.render() for f in errors]
        super().__init__("\n".join(lines))
