"""Lock-discipline lint over the runtime source tree (``PWC4xx``).

The threaded runtime (metrics samplers, heartbeat/election threads, the
device-pipeline completion worker, the serving pool) shares state under
a small set of locks.  The discipline is declared in the source with
``# guarded-by: <lock>`` comments on the attribute assignments in
``__init__``::

    self._staged = deque()  # guarded-by: self._cv

and this pass enforces it syntactically:

- ``PWC401`` — a guarded attribute is written (assigned, subscripted,
  deleted, or mutated through ``append``/``pop``/``update``/…) outside a
  ``with <lock>:`` block.  ``__init__`` is exempt (construction is
  single-threaded), and so are methods whose name ends in ``_locked``
  (the caller-holds-the-lock convention, e.g. ``_truncate_locked``).
- ``PWC402`` — two locks are acquired in inconsistent orders somewhere
  in the analyzed file set (a potential deadlock cycle).  Nesting is
  tracked through ``with`` blocks and one level of intra-module calls.
- ``PWC403`` — a blocking call (socket I/O, ``queue.get()`` with no
  timeout, ``time.sleep``, device sync, subprocess) runs while a lock is
  held.  ``cv.wait()`` on the *held* condition is exempt — it releases.
- ``PWC404`` — a thread-target function loops on an unbounded
  ``.get()`` / ``.wait()``: shutdown can hang the daemon forever.
- ``PWC405`` — a ``guarded-by`` comment names a lock that never appears
  in the class (annotation typo).

A ``# pwc-ok: PWC403 <reason>`` trailing comment waives one code on
that line (see ``analysis.source``).

Condition variables are unified with the lock they wrap: the lint
resolves ``self._cv = threading.Condition(self._lock)`` so holding
either name satisfies a guard on the other.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from pathway_tpu.analysis.findings import Report
from pathway_tpu.analysis.source import SourceModule, emit

#: receivers that look like locks when used as a ``with`` context
_LOCKISH = re.compile(r"(lock|mutex|_cv\b|cond)", re.IGNORECASE)

#: method calls that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "rotate",
}

#: calls that block unconditionally
_BLOCKING_ALWAYS = {
    "sleep", "accept", "connect", "sendall", "recv", "recv_into",
    "urlopen", "block_until_ready", "check_output", "check_call",
    "getaddrinfo",
}
_BLOCKING_DOTTED = {"subprocess.run", "subprocess.Popen"}

#: calls that block unless bounded by a ``timeout=`` argument
_BLOCKING_NO_TIMEOUT = {"wait", "wait_for", "result"}


def _dotted(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _has_timeout(call: ast.Call, attr: str | None = None) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            )
    # positional timeouts: wait(t), result(t), wait_for(pred, t)
    if attr in ("wait", "result") and call.args:
        a = call.args[0]
        return not (isinstance(a, ast.Constant) and a.value is None)
    if attr == "wait_for" and len(call.args) >= 2:
        a = call.args[1]
        return not (isinstance(a, ast.Constant) and a.value is None)
    return False


def _is_queue_get(call: ast.Call) -> bool:
    """``q.get()`` with zero positional args and no bound — ``dict.get``
    always passes the key positionally, so this shape is queue-like."""
    if not isinstance(call.func, ast.Attribute) or call.func.attr != "get":
        return False
    if call.args:
        return False
    if _has_timeout(call):
        return False
    for kw in call.keywords:
        if kw.arg == "block":
            return False
    return True


def _expr_nodes(node: ast.AST):
    """Walk an expression/statement without descending into nested
    function/class scopes (they are analyzed as their own scopes)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ) and n is not node:
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


@dataclass
class _FuncInfo:
    qualname: str
    node: ast.AST
    cls: str | None
    mod: SourceModule
    #: lock ids acquired anywhere in the body (for one-level call edges)
    acquires: set[str] = field(default_factory=set)
    #: (qualname-candidates, held-at-callsite, line)
    calls: list[tuple[list[str], tuple[str, ...], int]] = field(
        default_factory=list
    )


class _ModuleLint:
    def __init__(self, mod: SourceModule, report: Report) -> None:
        self.mod = mod
        self.report = report
        #: class -> attr -> lock text as annotated (e.g. "self._lock")
        self.guards: dict[str, dict[str, str]] = {}
        #: class -> alias groups of lock names (cv <-> wrapped lock)
        self.aliases: dict[str, list[set[str]]] = {}
        #: class -> every lock-ish name seen in a with/acquire/__init__
        self.seen_locks: dict[str, set[str]] = {}
        #: guard annotations at module scope: global var -> lock text
        self.module_guards: dict[str, str] = {}
        self.thread_targets: set[str] = set()
        self.funcs: list[_FuncInfo] = []

    # -- discovery --------------------------------------------------------

    def discover(self) -> None:
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    t = _dotted(kw.value)
                    if t:
                        self.thread_targets.add(t.split(".")[-1])
        for stmt in self.mod.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                lock = self.mod.guard_comments.get(stmt.lineno)
                if lock:
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.module_guards[t.id] = lock
        for stmt in self.mod.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._discover_class(stmt)

    def _discover_class(self, cls: ast.ClassDef) -> None:
        guards: dict[str, str] = {}
        aliases: list[set[str]] = []
        seen: set[str] = set()
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    lock = self.mod.guard_comments.get(node.lineno)
                    for t in targets:
                        td = _dotted(t)
                        if not td or not td.startswith("self."):
                            continue
                        if lock:
                            guards[td[len("self."):]] = lock
                        if fn.name == "__init__":
                            if _LOCKISH.search(td):
                                seen.add(td)
                            # unify Condition(lock) with its inner lock
                            v = node.value if isinstance(node, ast.Assign) \
                                else node.value
                            if isinstance(v, ast.Call):
                                vf = _dotted(v.func) or ""
                                if vf.split(".")[-1] == "Condition" and v.args:
                                    inner = _dotted(v.args[0])
                                    if inner:
                                        aliases.append({td, inner})
                elif isinstance(node, ast.With):
                    for item in node.items:
                        t = _dotted(item.context_expr)
                        if t and _LOCKISH.search(t):
                            seen.add(t)
                elif isinstance(node, ast.Call):
                    f = _dotted(node.func)
                    if f and f.endswith(".acquire"):
                        seen.add(f[: -len(".acquire")])
        self.guards[cls.name] = guards
        self.aliases[cls.name] = aliases
        self.seen_locks[cls.name] = seen
        # PWC405: annotation names a lock the class never touches
        for attr, lock in guards.items():
            if lock in self.module_guards.values():
                continue
            known = seen | {
                a for group in aliases for a in group
            }
            if lock not in known and f"self.{lock}" not in known:
                for line, name in self.mod.guard_comments.items():
                    if name == lock:
                        emit(
                            self.report, self.mod, "PWC405", line,
                            f"attribute {cls.name}.{attr} is guarded by "
                            f"{lock!r}, but that lock is never created or "
                            f"acquired in class {cls.name}",
                        )
                        break

    # -- alias closure ----------------------------------------------------

    def _closure(self, cls: str | None, names: tuple[str, ...]) -> set[str]:
        out = set(names)
        for group in self.aliases.get(cls or "", []):
            if out & group:
                out |= group
        return out

    def _holds(self, cls: str | None, held: tuple[str, ...], lock: str) -> bool:
        closed = self._closure(cls, held)
        return lock in closed or f"self.{lock}" in closed

    # -- per-function walk ------------------------------------------------

    def lock_id(self, cls: str | None, text: str) -> str:
        """Normalize a lock name for the cross-file order graph."""
        if text.startswith("self.") and cls:
            return f"{cls}.{text[len('self.'):]}"
        if "." not in text:
            return f"{self.mod.stem}.{text}"
        return text

    def analyze_functions(self) -> None:
        def visit_scope(
            fn: ast.AST, cls: str | None, qual: str
        ) -> None:
            info = _FuncInfo(qualname=qual, node=fn, cls=cls, mod=self.mod)
            self.funcs.append(info)
            is_target = fn.name in self.thread_targets
            exempt_401 = fn.name == "__init__" or fn.name.endswith("_locked")
            self._walk_block(
                fn.body, (), info, cls,
                loop_depth=0, is_target=is_target, exempt_401=exempt_401,
            )
            for st in ast.walk(fn):
                if (
                    isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and st is not fn
                ):
                    visit_scope(st, cls, f"{qual}.{st.name}")

        for stmt in self.mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_scope(stmt, None, stmt.name)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        visit_scope(sub, stmt.name, f"{stmt.name}.{sub.name}")

    def _walk_block(
        self,
        stmts: list[ast.stmt],
        held: tuple[str, ...],
        info: _FuncInfo,
        cls: str | None,
        *,
        loop_depth: int,
        is_target: bool,
        exempt_401: bool,
    ) -> None:
        kw = dict(loop_depth=loop_depth, is_target=is_target,
                  exempt_401=exempt_401)
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # separate scope
            if isinstance(st, (ast.With, ast.AsyncWith)):
                acquired: list[str] = []
                for item in st.items:
                    t = _dotted(item.context_expr)
                    if t and _LOCKISH.search(t):
                        acquired.append(t)
                    else:
                        self._scan(item.context_expr, held, info, cls, **kw)
                for t in acquired:
                    tid = self.lock_id(cls, t)
                    info.acquires.add(tid)
                    for h in held:
                        hid = self.lock_id(cls, h)
                        if hid != tid and not (
                            self._closure(cls, (h,)) & self._closure(cls, (t,))
                        ):
                            _ORDER_EDGES.setdefault(hid, {}).setdefault(
                                tid, (self.mod, st.lineno)
                            )
                self._walk_block(
                    st.body, held + tuple(acquired), info, cls, **kw
                )
            elif isinstance(st, ast.If):
                self._scan(st.test, held, info, cls, **kw)
                self._walk_block(st.body, held, info, cls, **kw)
                self._walk_block(st.orelse, held, info, cls, **kw)
            elif isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
                header = st.test if isinstance(st, ast.While) else st.iter
                self._scan(header, held, info, cls, **kw)
                inner = dict(kw)
                inner["loop_depth"] = loop_depth + 1
                self._walk_block(st.body, held, info, cls, **inner)
                self._walk_block(st.orelse, held, info, cls, **inner)
            elif isinstance(st, ast.Try):
                for block in (st.body, st.orelse, st.finalbody):
                    self._walk_block(block, held, info, cls, **kw)
                for handler in st.handlers:
                    self._walk_block(handler.body, held, info, cls, **kw)
            else:
                self._scan(st, held, info, cls, **kw)

    # -- expression checks ------------------------------------------------

    def _scan(
        self,
        node: ast.AST | None,
        held: tuple[str, ...],
        info: _FuncInfo,
        cls: str | None,
        *,
        loop_depth: int,
        is_target: bool,
        exempt_401: bool,
    ) -> None:
        if node is None:
            return
        for n in _expr_nodes(node):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    n.targets if isinstance(n, ast.Assign) else [n.target]
                )
                for t in targets:
                    self._check_write(t, held, cls, n.lineno, exempt_401)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    self._check_write(t, held, cls, n.lineno, exempt_401)
            elif isinstance(n, ast.Call):
                self._check_call(
                    n, held, info, cls,
                    loop_depth=loop_depth, is_target=is_target,
                    exempt_401=exempt_401,
                )

    def _guard_for(self, cls: str | None, target: ast.AST) -> tuple[str, str] | None:
        """(attr, lock) when ``target`` writes a guarded location."""
        if isinstance(target, ast.Subscript):
            target = target.value
        td = _dotted(target)
        if td is None:
            return None
        if td.startswith("self.") and cls:
            attr = td[len("self."):].split(".")[0]
            lock = self.guards.get(cls, {}).get(attr)
            if lock:
                return attr, lock
        elif "." not in td:
            lock = self.module_guards.get(td)
            if lock:
                return td, lock
        return None

    def _check_write(
        self,
        target: ast.AST,
        held: tuple[str, ...],
        cls: str | None,
        line: int,
        exempt_401: bool,
    ) -> None:
        if exempt_401:
            return
        hit = self._guard_for(cls, target)
        if hit is None:
            return
        attr, lock = hit
        if self._holds(cls, held, lock):
            return
        where = f"{cls}.{attr}" if cls else attr
        emit(
            self.report, self.mod, "PWC401", line,
            f"write to {where} (guarded-by {lock}) without holding {lock}",
        )

    def _check_call(
        self,
        call: ast.Call,
        held: tuple[str, ...],
        info: _FuncInfo,
        cls: str | None,
        *,
        loop_depth: int,
        is_target: bool,
        exempt_401: bool,
    ) -> None:
        f = call.func
        fd = _dotted(f)
        attr = None
        recv = None
        if isinstance(f, ast.Attribute):
            attr = f.attr
            recv = _dotted(f.value)
        elif isinstance(f, ast.Name):
            attr = f.id
        line = call.lineno

        # PWC401 via in-place mutation of a guarded container
        if (
            not exempt_401
            and attr in _MUTATORS
            and recv is not None
        ):
            hit = self._guard_for(cls, f.value)
            if hit is not None:
                a, lock = hit
                if not self._holds(cls, held, lock):
                    where = f"{cls}.{a}" if cls else a
                    emit(
                        self.report, self.mod, "PWC401", line,
                        f"{attr}() mutates {where} (guarded-by {lock}) "
                        f"without holding {lock}",
                    )

        # record intra-module call edges for the lock-order graph
        if held and fd:
            candidates: list[str] = []
            if fd.startswith("self.") and cls and "." not in fd[5:]:
                candidates.append(f"{cls}.{fd[5:]}")
            elif "." not in fd:
                candidates.append(fd)
            if candidates:
                info.calls.append(
                    (candidates, held, line)
                )

        if not attr:
            return

        # PWC404: unbounded wait in a daemon/thread-target loop
        if is_target and loop_depth > 0:
            if _is_queue_get(call):
                emit(
                    self.report, self.mod, "PWC404", line,
                    f"thread target {info.qualname} loops on "
                    f"{recv or '?'}.get() with no timeout — shutdown can "
                    "hang this thread",
                )
            elif attr in ("wait", "wait_for") and not _has_timeout(call, attr):
                emit(
                    self.report, self.mod, "PWC404", line,
                    f"thread target {info.qualname} loops on "
                    f"{recv or '?'}.{attr}() with no timeout — shutdown "
                    "can hang this thread",
                )

        # PWC403: blocking call while a lock is held
        if not held:
            return
        blocking: str | None = None
        if attr in _BLOCKING_ALWAYS or (fd in _BLOCKING_DOTTED):
            blocking = f"{fd or attr}()"
        elif attr in _BLOCKING_NO_TIMEOUT and not _has_timeout(call, attr):
            # waiting on the held condition releases it — that is the
            # point of a CV — so only foreign waits are blocking here
            if not (recv and self._holds(cls, held, recv)):
                blocking = f"{fd or attr}() with no timeout"
        elif _is_queue_get(call):
            blocking = f"{fd or attr}() with no timeout"
        if blocking:
            locks = ", ".join(held)
            emit(
                self.report, self.mod, "PWC403", line,
                f"blocking {blocking} while holding {locks}",
            )


#: cross-file lock-order graph: lock -> lock -> (module, line) witness
_ORDER_EDGES: dict[str, dict[str, tuple[SourceModule, int]]] = {}


def _propagate_call_edges(lints: list[_ModuleLint]) -> None:
    """One level of interprocedural nesting: calling ``f()`` while
    holding A adds A -> (every lock f acquires, transitively)."""
    by_name: dict[str, list[_FuncInfo]] = {}
    for lint in lints:
        for fn in lint.funcs:
            by_name.setdefault(fn.qualname, []).append(fn)
            by_name.setdefault(fn.qualname.split(".")[-1], []).append(fn)

    closure_cache: dict[int, set[str]] = {}

    def closure(fn: _FuncInfo, depth: int = 0) -> set[str]:
        key = id(fn)
        if key in closure_cache:
            return closure_cache[key]
        closure_cache[key] = set(fn.acquires)  # break recursion cycles
        out = set(fn.acquires)
        if depth < 3:
            for candidates, _held, _line in fn.calls:
                for cand in candidates:
                    for callee in by_name.get(cand, []):
                        if callee is not fn:
                            out |= closure(callee, depth + 1)
        closure_cache[key] = out
        return out

    for lint in lints:
        for fn in lint.funcs:
            for candidates, held, line in fn.calls:
                acquired: set[str] = set()
                for cand in candidates:
                    for callee in by_name.get(cand, []):
                        if callee is not fn:
                            acquired |= closure(callee)
                for h in held:
                    hid = lint.lock_id(fn.cls, h)
                    for tid in acquired:
                        if tid != hid:
                            _ORDER_EDGES.setdefault(hid, {}).setdefault(
                                tid, (lint.mod, line)
                            )


def _report_cycles(report: Report) -> None:
    seen_cycles: set[frozenset[str]] = set()
    path: list[str] = []
    on_path: set[str] = set()
    visited: set[str] = set()

    def dfs(node: str) -> None:
        visited.add(node)
        on_path.add(node)
        path.append(node)
        for nxt in sorted(_ORDER_EDGES.get(node, {})):
            if nxt in on_path:
                cycle = path[path.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    mod, line = _ORDER_EDGES[node][nxt]
                    emit(
                        report, mod, "PWC402", line,
                        "inconsistent lock order (deadlock cycle): "
                        + " -> ".join(cycle),
                    )
            elif nxt not in visited:
                dfs(nxt)
        path.pop()
        on_path.discard(node)

    for node in sorted(_ORDER_EDGES):
        if node not in visited:
            dfs(node)


def run_pass(modules: list[SourceModule], report: Report) -> None:
    _ORDER_EDGES.clear()
    lints = []
    for mod in modules:
        lint = _ModuleLint(mod, report)
        lint.discover()
        lint.analyze_functions()
        lints.append(lint)
    _propagate_call_edges(lints)
    _report_cycles(report)
