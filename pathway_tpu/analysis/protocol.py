"""Scheduler/mesh protocol invariant checks (``PWC5xx``).

These are source-level cross-checks of the invariants the threaded
runtime's correctness rests on — the ones that have historically broken
as silent drift between two distant call sites:

- ``PWC501`` — **commit seam ordering.**  A checkpoint, operator
  snapshot, or read-snapshot publish for commit N may only be cut once
  N's staged device work has drained.  In any function that runs a
  commit hook (``publish_on_commit`` or a snapshot manager's
  ``on_commit``), a ``drain_until``/``drain`` call must appear *earlier
  in the same function body*.
- ``PWC502`` — **rollback reaches truncate.**  Readers must never
  observe commits the mesh rolled back past: every function whose name
  mentions ``rollback`` must reach a ``truncate`` call through the
  analyzed call graph.
- ``PWC503`` — **frame arity agreement.**  For each mesh frame kind
  (first element of a tuple passed to ``send``/``broadcast``), every
  encode site that builds a *fixed-shape* frame must agree on arity
  with every decode site that destructures it — the 6-tuple→8-tuple
  drift class.  Variable-length command frames are checked against the
  highest subscript a decoder reads.
- ``PWC504`` — **epoch-fence coverage.**  Any function that dispatches
  on a fenced control-frame kind (``== "recover"`` / ``"rollback"`` /
  ``"elect"``) must call ``fence.admit("<kind>", …)`` somewhere in the
  same function, so zombie-leader/duplicated commands stay no-ops.

All four checks run over the whole analyzed file set at once, so
encode/decode pairs living in different modules still cross-check.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from pathway_tpu.analysis.findings import Report
from pathway_tpu.analysis.source import SourceModule, emit

#: commit hooks that must follow a device drain
_HOOK_PUBLISH = "publish_on_commit"
_HOOK_ON_COMMIT = "on_commit"
#: ``.on_commit`` only counts when the receiver is snapshot machinery —
#: monitor/fault-plan hooks sit outside the exactly-once seam
_SNAPSHOT_RECV = "snapshot"
_DRAIN_CALLS = {"drain_until", "drain"}

_SEND_CALLS = {"send", "_send", "broadcast"}
#: frame kinds whose dispatch sites must consult an epoch fence: mesh
#: control commands plus the read tier's snapshot-stream data/rollback
#: frames (a zombie publisher's snapshots must never be restored)
_FENCED_KINDS = {"recover", "rollback", "elect", "snap", "snap-rollback"}


def _dotted(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _call_attr(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _own_nodes(fn: ast.AST):
    """Walk a function body without descending into nested defs."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _functions(mod: SourceModule):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


@dataclass
class _FrameKind:
    #: (module, line, arity) per tuple-literal encode site
    encodes: list[tuple[SourceModule, int, int]] = field(default_factory=list)
    #: (module, line, arity) per fixed tuple-unpack decode site
    unpacks: list[tuple[SourceModule, int, int]] = field(default_factory=list)


# -- PWC501 ----------------------------------------------------------------


def _check_commit_ordering(mod: SourceModule, report: Report) -> None:
    for fn in _functions(mod):
        drains: list[int] = []
        hooks: list[tuple[int, str]] = []
        for n in _own_nodes(fn):
            if not isinstance(n, ast.Call):
                continue
            attr = _call_attr(n)
            if attr in _DRAIN_CALLS:
                drains.append(n.lineno)
            elif attr == _HOOK_PUBLISH:
                hooks.append((n.lineno, _HOOK_PUBLISH))
            elif attr == _HOOK_ON_COMMIT and isinstance(
                n.func, ast.Attribute
            ):
                recv = _dotted(n.func.value) or ""
                if _SNAPSHOT_RECV in recv:
                    hooks.append((n.lineno, f"{recv}.on_commit"))
        if not hooks:
            continue
        first_drain = min(drains) if drains else None
        for line, what in hooks:
            if first_drain is None:
                emit(
                    report, mod, "PWC501", line,
                    f"{what}() in {fn.name} has no preceding "
                    "device_pipeline drain — staged device work for this "
                    "commit may be missing from the cut state",
                )
            elif line < first_drain:
                emit(
                    report, mod, "PWC501", line,
                    f"{what}() in {fn.name} runs before the drain at "
                    f"line {first_drain} — commit hooks must follow "
                    "drain_until",
                )


# -- PWC502 ----------------------------------------------------------------


def _check_rollback_truncate(
    modules: list[SourceModule], report: Report
) -> None:
    defs: dict[str, list[tuple[SourceModule, ast.AST]]] = {}
    for mod in modules:
        for fn in _functions(mod):
            defs.setdefault(fn.name, []).append((mod, fn))

    reach_cache: dict[int, bool] = {}

    def reaches_truncate(fn: ast.AST, depth: int = 0) -> bool:
        key = id(fn)
        if key in reach_cache:
            return reach_cache[key]
        reach_cache[key] = False  # break recursion
        out = False
        for n in _own_nodes(fn):
            if not isinstance(n, ast.Call):
                continue
            attr = _call_attr(n)
            if attr and "truncate" in attr:
                out = True
                break
            if attr and depth < 4:
                for _m, callee in defs.get(attr, []):
                    if callee is not fn and reaches_truncate(
                        callee, depth + 1
                    ):
                        out = True
                        break
            if out:
                break
        reach_cache[key] = out
        return out

    for mod in modules:
        for fn in _functions(mod):
            if "rollback" not in fn.name:
                continue
            if not reaches_truncate(fn):
                emit(
                    report, mod, "PWC502", fn.lineno,
                    f"rollback path {fn.name}() never reaches a snapshot "
                    "truncate() — readers could observe rolled-back "
                    "commits",
                )


# -- PWC503 ----------------------------------------------------------------


def _collect_frames(
    modules: list[SourceModule],
) -> tuple[
    dict[str, _FrameKind],
    list[tuple[SourceModule, int, int, frozenset[str]]],
]:
    kinds: dict[str, _FrameKind] = {}
    #: indexed decode sites: (module, line, max index, kinds the decoded
    #: variable is compared against — one var can carry several kinds)
    sub_checks: list[tuple[SourceModule, int, int, frozenset[str]]] = []

    def kind_for(name: str) -> _FrameKind:
        return kinds.setdefault(name, _FrameKind())

    for mod in modules:
        for fn in _functions(mod):
            # encode sites: tuple literals handed to send/broadcast
            for n in _own_nodes(fn):
                if isinstance(n, ast.Call) and _call_attr(n) in _SEND_CALLS:
                    for arg in n.args:
                        if (
                            isinstance(arg, ast.Tuple)
                            and arg.elts
                            and isinstance(arg.elts[0], ast.Constant)
                            and isinstance(arg.elts[0].value, str)
                        ):
                            kind_for(arg.elts[0].value).encodes.append(
                                (mod, n.lineno, len(arg.elts))
                            )
            # decode sites: variables assigned from a recv-ish call
            recv_vars: set[str] = set()
            for n in _own_nodes(fn):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)
                ):
                    attr = _call_attr(n.value) or ""
                    if "recv" in attr:
                        recv_vars.add(n.targets[0].id)
            if not recv_vars:
                continue
            # fixed unpacks: (a, b, ...) = frame, kind named by a later
            # comparison of the first target against a string constant
            unpack_first: dict[str, tuple[SourceModule, int, int]] = {}
            sub_max: dict[str, int] = {}
            sub_line: dict[str, int] = {}
            var_kinds: dict[str, set[str]] = {}
            # two sub-passes: _own_nodes yields statements in stack
            # order, so a ``kind == "round"`` comparison can be visited
            # before the unpack that binds ``kind`` — collect every
            # unpack/subscript first, then resolve the comparisons
            for n in _own_nodes(fn):
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Tuple)
                    and isinstance(n.value, ast.Name)
                    and n.value.id in recv_vars
                ):
                    elts = n.targets[0].elts
                    if elts and all(isinstance(e, ast.Name) for e in elts):
                        unpack_first[elts[0].id] = (mod, n.lineno, len(elts))
                if (
                    isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id in recv_vars
                    and isinstance(n.slice, ast.Constant)
                    and isinstance(n.slice.value, int)
                ):
                    v = n.value.id
                    if n.slice.value > sub_max.get(v, -1):
                        sub_max[v] = n.slice.value
                        sub_line[v] = n.lineno
            for n in _own_nodes(fn):
                if isinstance(n, ast.Compare) and len(n.ops) == 1 and (
                    isinstance(n.ops[0], (ast.Eq, ast.NotEq))
                ):
                    left, right = n.left, n.comparators[0]
                    if not (
                        isinstance(right, ast.Constant)
                        and isinstance(right.value, str)
                    ):
                        continue
                    # frame[0] == "kind"
                    if (
                        isinstance(left, ast.Subscript)
                        and isinstance(left.value, ast.Name)
                        and left.value.id in recv_vars
                        and isinstance(left.slice, ast.Constant)
                        and left.slice.value == 0
                    ):
                        var_kinds.setdefault(left.value.id, set()).add(
                            right.value
                        )
                    # kind == "round" where kind was the first unpack name
                    elif (
                        isinstance(left, ast.Name)
                        and left.id in unpack_first
                    ):
                        m, line, arity = unpack_first[left.id]
                        kind_for(right.value).unpacks.append((m, line, arity))
            for var, names in var_kinds.items():
                if var in sub_max:
                    sub_checks.append(
                        (mod, sub_line[var], sub_max[var], frozenset(names))
                    )
    return kinds, sub_checks


def _check_frame_arity(modules: list[SourceModule], report: Report) -> None:
    kinds, sub_checks = _collect_frames(modules)
    for name, fk in sorted(kinds.items()):
        if not fk.encodes:
            continue
        if fk.unpacks:
            expected = fk.unpacks[0][2]
            for m, line, arity in fk.unpacks[1:]:
                if arity != expected:
                    emit(
                        report, m, "PWC503", line,
                        f"frame kind {name!r} is destructured into "
                        f"{arity} fields here but {expected} elsewhere",
                    )
            for m, line, arity in fk.encodes:
                if arity != expected:
                    emit(
                        report, m, "PWC503", line,
                        f"frame kind {name!r} encoded with {arity} "
                        f"element(s) but decoders destructure "
                        f"{expected} — encode/decode drift",
                    )
    for m, line, max_idx, names in sub_checks:
        arities = [
            a
            for name in names
            for _m, _l, a in kinds.get(name, _FrameKind()).encodes
        ]
        if not arities:
            continue  # no literal encode site in the analyzed set
        if max_idx >= max(arities):
            shown = "/".join(sorted(names))
            emit(
                report, m, "PWC503", line,
                f"decoder reads {shown!r} frame element [{max_idx}] "
                f"but no encoder builds more than {max(arities)} "
                "element(s)",
            )


# -- PWC504 ----------------------------------------------------------------


def _check_epoch_fences(mod: SourceModule, report: Report) -> None:
    for fn in _functions(mod):
        dispatched: dict[str, int] = {}
        admitted: set[str] = set()
        for n in _own_nodes(fn):
            if isinstance(n, ast.Compare) and len(n.ops) == 1 and isinstance(
                n.ops[0], (ast.Eq, ast.NotEq)
            ):
                right = n.comparators[0]
                if (
                    isinstance(right, ast.Constant)
                    and isinstance(right.value, str)
                    and right.value in _FENCED_KINDS
                ):
                    dispatched.setdefault(right.value, n.lineno)
            elif isinstance(n, ast.Call) and _call_attr(n) == "admit":
                if n.args and isinstance(n.args[0], ast.Constant):
                    admitted.add(n.args[0].value)
        for kind, line in sorted(dispatched.items()):
            if kind not in admitted:
                emit(
                    report, mod, "PWC504", line,
                    f"{fn.name}() dispatches on control frame "
                    f"{kind!r} without fencing it "
                    f'(fence.admit("{kind}", epoch)) — a zombie leader '
                    "or duplicated command would be re-executed",
                )


def run_pass(modules: list[SourceModule], report: Report) -> None:
    for mod in modules:
        _check_commit_ordering(mod, report)
        _check_epoch_fences(mod, report)
    _check_rollback_truncate(modules, report)
    _check_frame_arity(modules, report)
