"""Source-tree analysis driver for the runtime concurrency, protocol,
and device-plane passes.

The graph passes (``analysis.dtypes`` … ``analysis.udf_lint``) need a
built :class:`~pathway_tpu.engine.graph.Scope`; the ``PWC``/``PWD``
passes lint the *runtime's own source* instead — the threads, locks,
mesh protocol, and device planes that execute the graph.  This module
owns the shared plumbing:

- collecting ``.py`` files from a mix of file and directory targets,
- parsing them once into :class:`SourceModule` records shared by the
  passes (``analysis.concurrency``, ``analysis.protocol``, and
  ``analysis.deviceplane``),
- per-line suppression comments (``# pwc-ok: PWC403`` waives one code on
  that line, bare ``# pwc-ok`` waives them all; ``# pwd-ok: PWD603``
  likewise for the device-plane family, bare ``# pwd-ok`` waives every
  PWD code — every waiver should carry a reason in the trailing text;
  waived findings are kept on ``report.waived`` for ``--json`` audit),
- the same crash isolation as :func:`analyze_scope`: a pass that raises
  lands in ``report.internal_errors`` (CLI exit 2), never in findings.

``PWC`` findings reuse :class:`Finding` with ``node_name`` = relative
file path and ``node_index`` = 1-based line number.
"""

from __future__ import annotations

import ast
import os
import re
import traceback
from dataclasses import dataclass, field

from pathway_tpu.analysis.findings import Finding, Report, Severity

GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
SUPPRESS_RE = re.compile(r"#\s*pwc-ok(?::\s*([A-Z0-9, ]+))?")
#: bare ``# pwd-ok`` waives only the PWD family (recorded as ``PWD*``),
#: unlike bare ``# pwc-ok`` which predates PWD and waives everything
PWD_SUPPRESS_RE = re.compile(r"#\s*pwd-ok(?::\s*([A-Z0-9, ]+))?")


@dataclass
class SourceModule:
    path: str
    rel: str
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)
    #: line -> waived codes for that line ({"*"} = all)
    suppress: dict[int, set[str]] = field(default_factory=dict)
    #: line -> lock name from a ``# guarded-by:`` comment
    guard_comments: dict[int, str] = field(default_factory=dict)

    @property
    def stem(self) -> str:
        return os.path.splitext(os.path.basename(self.path))[0]


def collect_files(targets: list[str]) -> list[str]:
    """Expand files/directories into a sorted list of ``.py`` paths."""
    out: list[str] = []
    for target in targets:
        if os.path.isdir(target):
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.join(dirpath, name))
        else:
            out.append(target)
    seen: set[str] = set()
    uniq = []
    for p in out:
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            uniq.append(ap)
    return uniq


def load_module(path: str, root: str | None = None) -> SourceModule:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    mod = SourceModule(
        path=path,
        rel=rel,
        source=source,
        tree=ast.parse(source, filename=path),
        lines=source.splitlines(),
    )
    for i, line in enumerate(mod.lines, start=1):
        if "#" not in line:
            continue
        g = GUARD_RE.search(line)
        if g:
            mod.guard_comments[i] = g.group(1)
        for regex, bare in ((SUPPRESS_RE, "*"), (PWD_SUPPRESS_RE, "PWD*")):
            m = regex.search(line)
            if not m:
                continue
            codes = m.group(1) or ""
            parsed = {c.strip() for c in codes.split(",") if c.strip()}
            # "# pwd-ok: some lowercase reason" parses no codes — that is
            # the bare form with a reason, not an empty waiver
            mod.suppress.setdefault(i, set()).update(parsed or {bare})
    return mod


def emit(
    report: Report,
    mod: SourceModule,
    code: str,
    line: int,
    message: str,
    severity: Severity | None = None,
) -> None:
    """Add a finding unless the line (or a standalone waiver comment on
    the line above) carries a matching waiver.  Waived findings are kept
    on ``report.waived`` (flagged ``waived=True``) so machine-readable
    output can audit them; they never affect counts or exit codes."""
    waivers = mod.suppress.get(line, set()) | mod.suppress.get(line - 1, set())
    from pathway_tpu.analysis.findings import FINDING_CODES

    is_waived = (
        "*" in waivers
        or code in waivers
        or ("PWD*" in waivers and code.startswith("PWD"))
    )
    finding = Finding(
        code=code,
        message=message,
        node_index=line,
        node_name=mod.rel,
        severity=severity or FINDING_CODES[code][0],
        waived=is_waived,
    )
    if is_waived:
        report.waived.append(finding)
    else:
        report.add(finding)


def analyze_paths(targets: list[str], root: str | None = None) -> Report:
    """Run the concurrency + protocol passes over source targets.

    Mirrors :func:`pathway_tpu.analysis.analyze_scope`: each pass is
    crash-isolated into ``internal_errors``; ``node_count`` counts the
    files analyzed.
    """
    from pathway_tpu.analysis import concurrency, deviceplane, protocol

    if root is None:
        root = os.getcwd()
    report = Report()
    modules: list[SourceModule] = []
    for path in collect_files(targets):
        try:
            modules.append(load_module(path, root=root))
        except (OSError, SyntaxError) as exc:
            report.internal_errors.append(f"cannot analyze {path}: {exc}")
    report.node_count = len(modules)
    for name, run in (
        ("concurrency", concurrency.run_pass),
        ("protocol", protocol.run_pass),
        ("deviceplane", deviceplane.run_pass),
    ):
        try:
            run(modules, report)
        except Exception:  # noqa: BLE001 — collected, not raised
            tail = traceback.format_exc(limit=4)
            report.internal_errors.append(f"pass {name!r} crashed: {tail}")
    return report
