"""Pass 4 — UDF determinism / purity lint.

AST-inspects the Python callables reachable from ``Apply`` expressions and
``BatchApplyNode.rows_fn``.  Nondeterminism inside a UDF the engine
believes is deterministic (``Apply.deterministic`` defaults True) silently
breaks replay and checkpoint parity: a replayed run recomputes different
values for the same keys, so retractions stop matching their insertions.

Five checks:

- ``PWA301`` (error) — calls into known nondeterminism sources
  (``random``, ``time``, ``uuid``, ``secrets``, ``os.urandom``,
  ``datetime.now``, ``id``) in a UDF marked deterministic;
- ``PWA302`` (warning) — iteration order over a ``set`` literal /
  comprehension / ``set()`` call feeding order-sensitive construction
  (``for`` loops, ``list()``/``tuple()``/``join`` — ``sorted()`` is fine);
- ``PWA303`` (warning) — ``global`` declarations that are assigned to,
  i.e. ambient state mutation across rows;
- ``PWA304`` (warning) — ``functools.lru_cache``/``cache`` on a UDF,
  detected both as a decorator in source and as a live cache wrapper
  (``cache_info``) — cached values survive retractions and replay;
- ``PWA305`` (warning) — mutable default arguments (``list``/``dict``/
  ``set``/``bytearray`` instances in ``__defaults__``), shared across
  every row and run.

Builtins, C extensions, and callables whose source cannot be retrieved are
skipped silently — the lint only ever inspects what it can parse, so it
cannot produce false positives on opaque callables.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, Iterator

from pathway_tpu.analysis.findings import Finding, Report, Severity
from pathway_tpu.engine import expression as ex
from pathway_tpu.engine import graph as g

#: dotted-call prefixes that are nondeterministic across runs
_NONDET_DOTTED = (
    "random.",
    "secrets.",
    "np.random.",
    "numpy.random.",
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.process_time",
    "time.time_ns",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "date.today",
    "datetime.date.today",
    "uuid.uuid1",
    "uuid.uuid4",
    "os.urandom",
    "os.getpid",
)

#: bare names that are nondeterministic when called directly
#: (``from random import random`` style imports, plus builtins)
_NONDET_BARE = {
    "id",
    "urandom",
    "uuid1",
    "uuid4",
    "random",
    "randint",
    "randrange",
    "getrandbits",
    "choice",
    "choices",
    "shuffle",
    "sample",
    "uniform",
    "gauss",
    "token_hex",
    "token_bytes",
    "perf_counter",
    "monotonic",
    "time_ns",
}


#: RNG constructors / reseeders that ARE deterministic when given an
#: explicit seed argument (stdlib.ml._lsh, xpacks.llm.mocks style:
#: ``np.random.default_rng(seed)``, ``random.Random(seed)``)
_SEEDABLE_SUFFIXES = (".default_rng", ".RandomState", ".Random", ".seed")


def _explicitly_seeded(name: str, call: "ast.Call") -> bool:
    if not (call.args or call.keywords):
        return False
    return name.endswith(_SEEDABLE_SUFFIXES) or name in (
        "default_rng",
        "RandomState",
        "Random",
    )


def _dotted_name(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_setish(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _dotted_name(node.func) in ("set", "frozenset")
    return False


class _UdfVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.nondet_calls: list[str] = []
        self.set_iterations: list[str] = []
        self.global_names: set[str] = set()
        self.mutated_globals: set[str] = set()
        self.cache_decorators: list[str] = []

    def _check_decorators(self, node: ast.AST) -> None:
        for dec in getattr(node, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = _dotted_name(target)
            if name and name.rsplit(".", 1)[-1] in ("lru_cache", "cache"):
                self.cache_decorators.append(name)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_decorators(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_decorators(node)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.global_names.update(node.names)
        self.generic_visit(node)

    def _check_assign_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name) and target.id in self.global_names:
            self.mutated_globals.add(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_assign_target(target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_assign_target(node.target)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted_name(node.func)
        if name is not None:
            if _explicitly_seeded(name, node):
                pass  # seeded RNG construction is deterministic
            elif any(name == p or name.startswith(p) for p in _NONDET_DOTTED):
                self.nondet_calls.append(name)
            elif "." not in name and name in _NONDET_BARE:
                self.nondet_calls.append(name)
            # list(set(...)), tuple({...}), "".join(set(...)) — but
            # sorted(set(...)) is deterministic
            if name in ("list", "tuple") or name.endswith(".join"):
                for arg in node.args:
                    if _is_setish(arg):
                        self.set_iterations.append(
                            f"{name}() over a set"
                        )
        self.generic_visit(node)

    def _check_iter(self, iter_node: ast.AST, where: str) -> None:
        if _is_setish(iter_node):
            self.set_iterations.append(where)

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter, "for loop over a set")
        self.generic_visit(node)

    def visit_comprehension_gens(self, generators) -> None:
        for gen in generators:
            self._check_iter(gen.iter, "comprehension over a set")

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)


def _candidate_functions(fn: Callable, depth: int = 0) -> Iterator[Callable]:
    """The function itself plus user functions hidden behind wrapper
    closures (the framework wraps UDFs in ``_make_kw_fn`` / executor
    shells before they reach the engine)."""
    if depth > 3 or not callable(fn):
        return
    if isinstance(fn, functools.partial):
        yield from _candidate_functions(fn.func, depth + 1)
        return
    seen = getattr(fn, "__wrapped__", None)
    if seen is not None:
        yield from _candidate_functions(seen, depth + 1)
    if inspect.isfunction(fn):
        yield fn
        for cell in fn.__closure__ or ():
            try:
                inner = cell.cell_contents
            except ValueError:
                continue
            if inspect.isfunction(inner):
                yield from _candidate_functions(inner, depth + 1)
    elif inspect.ismethod(fn):
        yield from _candidate_functions(fn.__func__, depth + 1)
        # pw.udf routes BatchApplyNode.rows_fn through a bound
        # execute_rows shell; the user's function sits on the instance
        inner = getattr(fn.__self__, "_fn", None)
        if callable(inner):
            yield from _candidate_functions(inner, depth + 1)
    elif hasattr(fn, "__call__") and inspect.isfunction(
        getattr(type(fn), "__call__", None)
    ):
        yield type(fn).__call__


def _shell_chain(fn: Callable, depth: int = 0) -> Iterator[Callable]:
    """``fn`` plus every wrapper shell met while unwrapping it — the
    objects a live ``cache_info`` probe must see, which candidate
    discovery (functions only) would skip over."""
    if depth > 4 or fn is None:
        return
    yield fn
    if isinstance(fn, functools.partial):
        yield from _shell_chain(fn.func, depth + 1)
    elif inspect.ismethod(fn):
        inner = getattr(fn.__self__, "_fn", None)
        if callable(inner):
            yield from _shell_chain(inner, depth + 1)
    else:
        wrapped = getattr(fn, "__wrapped__", None)
        if wrapped is not None:
            yield from _shell_chain(wrapped, depth + 1)


def _default_args(fn: Callable) -> list[tuple[str, object]]:
    """(name, default value) pairs, positional and keyword-only."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return []
    out: list[tuple[str, object]] = []
    defaults = fn.__defaults__ or ()
    if defaults:
        names = code.co_varnames[: code.co_argcount][-len(defaults):]
        out.extend(zip(names, defaults))
    out.extend((fn.__kwdefaults__ or {}).items())
    return out


def _parse(fn: Callable) -> ast.AST | None:
    try:
        source = textwrap.dedent(inspect.getsource(fn))
        return ast.parse(source)
    except (OSError, TypeError, SyntaxError, IndentationError, ValueError):
        return None


def lint_callable(
    fn: Callable,
    node: g.Node,
    report: Report,
    *,
    deterministic: bool = True,
    what: str = "UDF",
) -> None:
    seen_src: set[int] = set()
    cache_reported = False
    # runtime route: fn (or a wrapper shell) IS an lru_cache/cache
    # wrapper — catches `udf = lru_cache(udf)` done after definition,
    # which never shows up in any candidate's source
    for shell in _shell_chain(fn):
        if hasattr(shell, "cache_info") and hasattr(shell, "cache_clear"):
            inner = getattr(shell, "__wrapped__", shell)
            fname = getattr(inner, "__name__", "<callable>")
            report.add(
                Finding(
                    code="PWA304",
                    message=(
                        f"{what} {fname!r} is wrapped in functools."
                        "lru_cache/cache — cached values survive "
                        "retractions and replay, so recomputed rows can "
                        "disagree with the original run"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.WARNING,
                    trace=getattr(node, "trace", None) or None,
                )
            )
            cache_reported = True
            break
    for candidate in _candidate_functions(fn):
        code = getattr(candidate, "__code__", None)
        if code is not None:
            if id(code) in seen_src:
                continue
            seen_src.add(id(code))
        # the framework's own wrapper shells (kw-arg adapters, executor
        # shims) are not user code — but stdlib/xpacks UDFs are ours to lint
        module = getattr(candidate, "__module__", "") or ""
        if module.startswith(("pathway_tpu.internals", "pathway_tpu.engine")):
            continue
        # needs only __defaults__, so it works even when the source is
        # unavailable (REPL / -c / generated callables)
        mutable_defaults = [
            name
            for name, value in _default_args(candidate)
            if isinstance(value, (list, dict, set, bytearray))
        ]
        if mutable_defaults:
            names = ", ".join(sorted(mutable_defaults))
            report.add(
                Finding(
                    code="PWA305",
                    message=(
                        f"{what} "
                        f"{getattr(candidate, '__name__', '<callable>')!r} "
                        f"has mutable default argument(s) ({names}) — the "
                        "default is shared across every row and run, so "
                        "any mutation leaks between keys"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.WARNING,
                    trace=getattr(node, "trace", None) or None,
                )
            )
        tree = _parse(candidate)
        if tree is None:
            continue
        visitor = _UdfVisitor()
        visitor.visit(tree)
        fname = getattr(candidate, "__name__", "<callable>")
        if visitor.nondet_calls and deterministic:
            calls = ", ".join(sorted(set(visitor.nondet_calls)))
            report.add(
                Finding(
                    code="PWA301",
                    message=(
                        f"{what} {fname!r} calls nondeterministic "
                        f"source(s) [{calls}] but is treated as "
                        "deterministic — replay and checkpoint parity "
                        "break (pass deterministic=False or remove the "
                        "call)"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.ERROR,
                    trace=getattr(node, "trace", None) or None,
                )
            )
        for where in sorted(set(visitor.set_iterations)):
            report.add(
                Finding(
                    code="PWA302",
                    message=(
                        f"{what} {fname!r}: {where} — set iteration order "
                        "depends on hash seeding; wrap in sorted() for a "
                        "stable order"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.WARNING,
                    trace=getattr(node, "trace", None) or None,
                )
            )
        if visitor.cache_decorators and not cache_reported:
            decs = ", ".join(sorted(set(visitor.cache_decorators)))
            report.add(
                Finding(
                    code="PWA304",
                    message=(
                        f"{what} {fname!r} carries caching decorator(s) "
                        f"[{decs}] — cached values survive retractions "
                        "and replay, so recomputed rows can disagree "
                        "with the original run"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.WARNING,
                    trace=getattr(node, "trace", None) or None,
                )
            )
            cache_reported = True
        if visitor.mutated_globals:
            names = ", ".join(sorted(visitor.mutated_globals))
            report.add(
                Finding(
                    code="PWA303",
                    message=(
                        f"{what} {fname!r} mutates global state "
                        f"({names}) — per-row results depend on "
                        "processing order"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.WARNING,
                    trace=getattr(node, "trace", None) or None,
                )
            )


def _apply_exprs(expr: ex.EngineExpression) -> Iterator[ex.Apply]:
    if isinstance(expr, ex.Apply):
        yield expr
    for slot in getattr(type(expr), "__slots__", ()):
        child = getattr(expr, slot, None)
        if isinstance(child, ex.EngineExpression):
            yield from _apply_exprs(child)
        elif isinstance(child, (list, tuple)):
            for item in child:
                if isinstance(item, ex.EngineExpression):
                    yield from _apply_exprs(item)


def run_pass(scope: g.Scope, report: Report) -> None:
    for node in scope.nodes:
        if isinstance(node, g.ExpressionNode):
            for expr in node.expressions:
                for apply_expr in _apply_exprs(expr):
                    lint_callable(
                        apply_expr.fn,
                        node,
                        report,
                        deterministic=apply_expr.deterministic,
                        what="apply UDF",
                    )
        elif isinstance(node, g.BatchApplyNode):
            lint_callable(
                node.rows_fn,
                node,
                report,
                deterministic=True,
                what="batch-apply UDF",
            )
