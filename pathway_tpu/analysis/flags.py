"""Declarative registry of ``PATHWAY_*`` environment flags.

The runtime's env flags fall into two liveness classes, and the split is
a documented API contract, not an implementation detail:

- ``live`` — re-read on **every** call/delivery/commit so operators can
  flip planes mid-run (``PATHWAY_TPU_COLLECTIVE_EXCHANGE=0`` must take
  effect on the next exchange, not the next process).  Caching one of
  these at import time silently freezes the plane and breaks the
  documented contract (PR 16/17 prose: "live per call", "live per
  delivery").
- ``startup`` — read once when the process (or subsystem) starts;
  changing them mid-run is documented to have no effect (ports, fault
  plans, trace ring sizes, ...).

``analysis.deviceplane`` consumes this registry for **PWD606**: a flag
registered here as ``live`` that is read and cached at module or class
scope is a flag-liveness violation.  Flags not registered here are left
alone by the analyzer, but keeping the registry complete is the point —
it is the single place the liveness contract is written down as data.
"""

from __future__ import annotations

from dataclasses import dataclass

LIVE = "live"
STARTUP = "startup"


@dataclass(frozen=True)
class FlagSpec:
    name: str
    liveness: str  # LIVE | STARTUP
    owner: str  # module that reads it
    help: str


def _spec(name: str, liveness: str, owner: str, help: str) -> FlagSpec:
    return FlagSpec(name=name, liveness=liveness, owner=owner, help=help)


#: name -> FlagSpec.  ``live`` entries are the per-call planes; everything
#: else is startup-scoped configuration.
REGISTRY: dict[str, FlagSpec] = {
    spec.name: spec
    for spec in (
        # -- live-per-call planes (PR 9/12/16/17 contracts) -------------
        _spec(
            "PATHWAY_TPU_COLLECTIVE_EXCHANGE",
            LIVE,
            "engine.collective_exchange",
            "0/1/auto — collective exchange plane, re-read per exchange",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_RESIDENCY",
            LIVE,
            "engine.device_residency",
            "0/1/auto — device-resident seam, re-read per delivery",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_OPS",
            LIVE,
            "engine.device_ops",
            "0/1/auto — device operator kernels, re-read per dispatch",
        ),
        _spec(
            "PATHWAY_TPU_ASYNC_DEVICE",
            LIVE,
            "engine.device_pipeline",
            "0/1 — async device pipeline, re-read per commit boundary",
        ),
        _spec(
            "PATHWAY_TPU_OPTIMIZE",
            LIVE,
            "optimize",
            "0/1 — graph rewriter escape hatch, re-read per run() start",
        ),
        _spec(
            "PATHWAY_TPU_RESULT_CACHE",
            LIVE,
            "serving.result_cache",
            "0/1 — serving result cache, re-read per lookup and insert",
        ),
        _spec(
            "PATHWAY_TPU_RESULT_CACHE_BYTES",
            LIVE,
            "serving.result_cache",
            "result-cache byte budget (64 MiB), re-read per insert",
        ),
        _spec(
            "PATHWAY_TPU_REPLICA_MAX_STALENESS_S",
            LIVE,
            "serving.replica",
            "replica staleness bound in seconds (5), re-read per query",
        ),
        # -- startup-scoped configuration -------------------------------
        _spec(
            "PATHWAY_TPU_VERIFY_ELISION",
            STARTUP,
            "engine.sharded",
            "1 — debug cross-check of elided exchange co-location",
        ),
        _spec(
            "PATHWAY_TPU_COLLECTIVE_MIN_ROWS",
            STARTUP,
            "engine.collective_exchange",
            "row floor below which collective exchange declines",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_OPS_MIN_ROWS",
            STARTUP,
            "engine.device_ops",
            "row floor below which device kernels decline",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_BATCH",
            STARTUP,
            "engine.device_pipeline",
            "initial adaptive device micro-batch size",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_BATCH_MIN",
            STARTUP,
            "engine.device_pipeline",
            "adaptive micro-batch lower bound",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_BATCH_MAX",
            STARTUP,
            "engine.device_pipeline",
            "adaptive micro-batch upper bound",
        ),
        _spec(
            "PATHWAY_TPU_DEVICE_INFLIGHT",
            STARTUP,
            "engine.device_pipeline",
            "staged-batch depth bound for the async pipeline",
        ),
        _spec(
            "PATHWAY_TPU_SERVING",
            STARTUP,
            "serving.server",
            "1 — start the per-process HTTP query front",
        ),
        _spec(
            "PATHWAY_TPU_SERVING_QUEUE",
            STARTUP,
            "serving.server",
            "admission-control queue bound",
        ),
        _spec(
            "PATHWAY_TPU_SERVING_THREADS",
            STARTUP,
            "serving.server",
            "query worker thread count",
        ),
        _spec(
            "PATHWAY_TPU_SERVING_BATCH_WINDOW_MS",
            STARTUP,
            "serving.server",
            "KNN micro-batch window",
        ),
        _spec(
            "PATHWAY_TPU_SERVING_PORT_BASE",
            STARTUP,
            "serving.server",
            "query-server port base (21000 + process id)",
        ),
        _spec(
            "PATHWAY_TPU_SERVING_STREAM_PORT_BASE",
            STARTUP,
            "serving.stream",
            "snapshot-stream port base (22000 + process id)",
        ),
        _spec(
            "PATHWAY_TPU_SERVING_FEDERATION",
            STARTUP,
            "serving.federation",
            "1 — leader-side federation front over the whole mesh",
        ),
        _spec(
            "PATHWAY_TPU_FEDERATION_PORT",
            STARTUP,
            "serving.federation",
            "federation front port (23000)",
        ),
        _spec(
            "PATHWAY_TPU_REPLICAS",
            STARTUP,
            "serving.federation",
            "replica pool: a count (port scheme) or host:port list",
        ),
        _spec(
            "PATHWAY_TPU_REPLICA_PORT_BASE",
            STARTUP,
            "serving.replica",
            "replica query port base (24000 + replica id)",
        ),
        _spec(
            "PATHWAY_TPU_LOCKWATCH",
            STARTUP,
            "internals.lockwatch",
            "1 — runtime lock-order-cycle recorder",
        ),
        _spec(
            "PATHWAY_TPU_PROFILE",
            STARTUP,
            "internals.profiling",
            "1 — sampling profiler",
        ),
        _spec(
            "PATHWAY_TPU_PROFILE_HZ",
            STARTUP,
            "internals.profiling",
            "profiler sample rate",
        ),
        _spec(
            "PATHWAY_TPU_PROFILE_DIR",
            STARTUP,
            "internals.profiling",
            "profiler export directory",
        ),
        _spec(
            "PATHWAY_TPU_TRACE",
            STARTUP,
            "internals.tracing",
            "1 — structured tracing",
        ),
        _spec(
            "PATHWAY_TPU_TRACE_DIR",
            STARTUP,
            "internals.tracing",
            "trace export directory",
        ),
        _spec(
            "PATHWAY_TPU_TRACE_RING",
            STARTUP,
            "internals.tracing",
            "trace ring capacity",
        ),
        _spec(
            "PATHWAY_TPU_TRACE_SAMPLE",
            STARTUP,
            "internals.tracing",
            "trace sampling ratio",
        ),
        _spec(
            "PATHWAY_TPU_REQUEST_TRACE",
            STARTUP,
            "internals.tracing",
            "1 — read-tier request tracing (X-Pathway-Trace)",
        ),
        _spec(
            "PATHWAY_TPU_REQUEST_TRACE_SAMPLE",
            STARTUP,
            "internals.tracing",
            "request-trace sampling interval",
        ),
        _spec(
            "PATHWAY_TPU_REQUEST_TRACE_RING",
            STARTUP,
            "internals.metrics",
            "wide-event request ring capacity",
        ),
        _spec(
            "PATHWAY_TPU_SLO",
            STARTUP,
            "internals.timeseries",
            "SLO sentinel policy document path / inline JSON",
        ),
        _spec(
            "PATHWAY_TPU_TIMESERIES",
            STARTUP,
            "internals.timeseries",
            "metrics history ring config",
        ),
        _spec(
            "PATHWAY_TPU_FLIGHT_DIR",
            STARTUP,
            "internals.metrics",
            "flight-event spool directory",
        ),
        _spec(
            "PATHWAY_TPU_FLIGHT_EVENTS",
            STARTUP,
            "internals.metrics",
            "flight-event ring capacity",
        ),
        _spec(
            "PATHWAY_TPU_ANALYZE",
            STARTUP,
            "analysis",
            "off/warn/strict — pre-execution graph analyzer mode",
        ),
        _spec(
            "PATHWAY_TPU_UDF_CACHE",
            STARTUP,
            "internals.udfs.caches",
            "UDF result-cache directory",
        ),
        _spec(
            "PATHWAY_TPU_DISABLE_NATIVE",
            STARTUP,
            "native",
            "1 — force the pure-python engine",
        ),
        _spec(
            "PATHWAY_TPU_FAULT_PLAN",
            STARTUP,
            "engine.faults",
            "chaos fault-plan JSON for seeded failure tests",
        ),
        _spec(
            "PATHWAY_TPU_RESTART_COUNT",
            STARTUP,
            "engine.faults",
            "supervisor restart generation counter",
        ),
        _spec(
            "PATHWAY_TPU_RECOVER",
            STARTUP,
            "internals.runner",
            "checkpoint directory to recover from",
        ),
        _spec(
            "PATHWAY_TPU_RECOVER_DEADLINE",
            STARTUP,
            "internals.runner",
            "recovery wall-clock deadline",
        ),
        _spec(
            "PATHWAY_TPU_RESHARD",
            STARTUP,
            "internals.runner",
            "checkpoint resharding target width",
        ),
        _spec(
            "PATHWAY_TPU_RESCALED",
            STARTUP,
            "internals.runner",
            "set by the supervisor on post-rescale restarts",
        ),
        _spec(
            "PATHWAY_TPU_RESCALE_WALL_S",
            STARTUP,
            "internals.runner",
            "rescale wall-clock budget",
        ),
        _spec(
            "PATHWAY_TPU_RESCALE_TIMEOUT",
            STARTUP,
            "engine.supervisor",
            "rescale barrier timeout",
        ),
        _spec(
            "PATHWAY_TPU_SUPERVISOR_DIR",
            STARTUP,
            "internals.runner",
            "supervisor scratch directory",
        ),
        _spec(
            "PATHWAY_TPU_MESH_TIMEOUT",
            STARTUP,
            "engine.distributed",
            "mesh handshake timeout",
        ),
        _spec(
            "PATHWAY_TPU_CONNECTOR_RETRIES",
            STARTUP,
            "engine.connectors",
            "external connector retry budget",
        ),
        _spec(
            "PATHWAY_EXCHANGE_COLUMNAR",
            STARTUP,
            "engine.distributed",
            "0/1 — columnar wire encoding for exchange frames",
        ),
        _spec(
            "PATHWAY_EXCHANGE_MAX_FRAME",
            STARTUP,
            "engine.distributed",
            "wire frame size bound",
        ),
        _spec(
            "PATHWAY_EXCHANGE_BIND",
            STARTUP,
            "engine.distributed",
            "exchange listener bind address",
        ),
        _spec(
            "PATHWAY_EXCHANGE_SECRET",
            STARTUP,
            "engine.distributed",
            "mesh frame HMAC secret",
        ),
        _spec(
            "PATHWAY_THREADS",
            STARTUP,
            "internals.runner",
            "worker thread count per process",
        ),
        _spec(
            "PATHWAY_PROCESSES",
            STARTUP,
            "internals.runner",
            "mesh process count",
        ),
        _spec(
            "PATHWAY_PROCESS_ID",
            STARTUP,
            "internals.runner",
            "this process's mesh rank",
        ),
        _spec(
            "PATHWAY_FIRST_PORT",
            STARTUP,
            "engine.distributed",
            "base port for mesh listeners",
        ),
        _spec(
            "PATHWAY_RUN_ID",
            STARTUP,
            "engine.connectors",
            "run identity for persistence namespacing",
        ),
        _spec(
            "PATHWAY_TELEMETRY_SERVER",
            STARTUP,
            "internals.telemetry",
            "telemetry export endpoint",
        ),
    )
}

#: flag names whose documented contract is re-read per call.
LIVE_FLAGS: frozenset[str] = frozenset(
    name for name, spec in REGISTRY.items() if spec.liveness == LIVE
)


def liveness_of(name: str) -> str | None:
    """Liveness class for ``name``, or ``None`` if unregistered."""
    spec = REGISTRY.get(name)
    return spec.liveness if spec else None
