"""Device-plane discipline lint (``PWD601``–``PWD607``).

The collective exchange (PR 16), device residency (PR 17), device
kernels (PR 12), and async pipeline (PR 9) live or die by conventions
that are stated in prose and enforced by hand.  This pass turns them
into findings over the runtime's own source:

- **PWD601** implicit device sync in a hot path — ``.item()`` /
  ``.tolist()`` / ``float()`` / ``int()`` / ``np.asarray()`` applied to
  a jnp-produced value inside operator ``process``/exchange/kernel code
  paths, outside an explicit materialize/fetch helper.  Each such call
  blocks the host on the device stream mid-path.
- **PWD602** recompile hazard — Python branching or loop bounds on a
  traced function's runtime array values or shapes.  Value branches
  raise at trace time; shape branches recompile per shape (the padding
  / bucketed-shape discipline exists to avoid exactly this).
- **PWD603** uncounted transfer — a ``jax.device_put`` / host
  materialization site in ``engine/`` whose function never touches the
  ``pathway_device_transfer_*`` ledger (``record_h2d``/``record_d2h``),
  violating PR 17's "counted in BOTH modes" rule.
- **PWD604** partial-push hazard — a decline or ``except`` path in
  exchange/residency delivery code that reaches a ``push``/deliver call
  without first materializing the whole buffer (the PR-6/16/17
  no-partial-push rollback invariant).
- **PWD605** residency leak — constructing a device-resident columns
  object whose class never registers instances for
  ``decay_resident_batches`` retirement, and with no registration at
  the construction site either.
- **PWD606** flag-liveness violation — a ``PATHWAY_*`` flag registered
  as ``live`` in :mod:`pathway_tpu.analysis.flags` read and cached at
  module or class scope.
- **PWD607** metric-family discipline — a ``pathway_*`` family name
  registered twice with different label sets, or used at an
  increment-style site without any registration in the analyzed set.

Waive intended exceptions with ``# pwd-ok: PWD6xx reason`` on the line
(or the line above); bare ``# pwd-ok`` waives every PWD code on that
line.  Findings use the shared source-lint provenance: ``node_name`` is
the relative file path, ``node_index`` the 1-based line.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from pathway_tpu.analysis.findings import Report
from pathway_tpu.analysis.flags import LIVE_FLAGS
from pathway_tpu.analysis.source import SourceModule, emit

# -- shared AST helpers ----------------------------------------------------


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _own_nodes(func: ast.AST) -> list[ast.AST]:
    """All nodes in ``func``'s own scope, not descending into nested
    function/lambda scopes (those are analyzed as their own units)."""
    out: list[ast.AST] = []
    stack = list(getattr(func, "body", []))
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPES):
                continue
            stack.append(child)
    return out


def _all_funcs(tree: ast.Module) -> list[tuple[ast.AST, str | None]]:
    """Every function/method in the module as ``(node, class_name)``."""
    out: list[tuple[ast.AST, str | None]] = []

    def visit(body: list[ast.stmt], cls: str | None) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((st, cls))
                visit(st.body, cls)
            elif isinstance(st, ast.ClassDef):
                visit(st.body, st.name)
    visit(tree.body, None)
    return out


def _calls(func: ast.AST) -> list[ast.Call]:
    return [n for n in _own_nodes(func) if isinstance(n, ast.Call)]


def _call_name(call: ast.Call) -> str:
    """Last path component of the call target (``a.b.c()`` -> ``c``)."""
    dotted = _dotted(call.func)
    return dotted.rsplit(".", 1)[-1] if dotted else ""


# -- PWD601: implicit device sync in hot paths -----------------------------

_HOT_MARKERS = ("exchange", "kernel", "deliver", "dispatch", "push")
_EXEMPT_MARKERS = ("materialize", "fetch", "decay", "host", "to_numpy")
_LEDGER_CALLS = {"record_h2d", "record_d2h", "record_saved"}
_DEVICE_PREFIXES = ("jnp.", "lax.", "jax.")
_SYNC_METHODS = {"item", "tolist"}


def _is_hot_path(name: str) -> bool:
    if name == "process":
        return True
    low = name.lower()
    if any(m in low for m in _EXEMPT_MARKERS):
        return False
    return any(m in low for m in _HOT_MARKERS)


def _device_producing(call: ast.Call) -> bool:
    dotted = _dotted(call.func)
    if any(dotted.startswith(p) for p in _DEVICE_PREFIXES):
        return True
    return "kernel" in dotted.lower()


def _device_vars(func: ast.AST) -> set[str]:
    """Names assigned (in ``func``'s own scope) from jnp/lax/kernel calls."""
    out: set[str] = set()
    for node in _own_nodes(func):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _device_producing(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.value, ast.Call
        ):
            if _device_producing(node.value) and isinstance(
                node.target, ast.Name
            ):
                out.add(node.target.id)
    return out


def _check_hot_sync(
    mod: SourceModule, func: ast.AST, report: Report
) -> None:
    if not _is_hot_path(func.name):
        return
    if any(_call_name(c) in _LEDGER_CALLS for c in _calls(func)):
        return  # explicit counted fetch — PWD603's jurisdiction
    dev = _device_vars(func)
    if not dev:
        return
    for call in _calls(func):
        line = call.lineno
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _SYNC_METHODS and isinstance(
                call.func.value, ast.Name
            ):
                if call.func.value.id in dev:
                    emit(
                        report,
                        mod,
                        "PWD601",
                        line,
                        f"hot path {func.name!r} syncs on device value "
                        f"{call.func.value.id!r} via "
                        f".{call.func.attr}() — move to a materialize/"
                        "fetch helper or batch the readback",
                    )
            continue
        dotted = _dotted(call.func)
        if dotted in ("float", "int") and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Name) and arg.id in dev:
                emit(
                    report,
                    mod,
                    "PWD601",
                    line,
                    f"hot path {func.name!r} forces device value "
                    f"{arg.id!r} to host via {dotted}() — implicit sync",
                )
        elif dotted in ("np.asarray", "numpy.asarray") and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Name) and arg.id in dev:
                emit(
                    report,
                    mod,
                    "PWD601",
                    line,
                    f"hot path {func.name!r} materializes device value "
                    f"{arg.id!r} via {dotted}() outside a counted "
                    "materialize/fetch helper",
                )


# -- PWD602: recompile hazard in traced functions --------------------------

_TRACE_WRAPPERS = ("jit", "shard_map", "shard_map_norep", "pmap", "xmap")


def _is_trace_wrapper(dotted: str) -> bool:
    last = dotted.rsplit(".", 1)[-1]
    return last in _TRACE_WRAPPERS


def _traced_names(mod: SourceModule) -> set[str]:
    """Local function names passed to jit/shard_map wrappers, plus names
    decorated with them."""
    traced: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _is_trace_wrapper(
            _dotted(node.func)
        ):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    traced.add(arg.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    # @jax.jit(...) or @partial(jax.jit, ...)
                    if _is_trace_wrapper(_dotted(dec.func)) or any(
                        _is_trace_wrapper(_dotted(a)) for a in dec.args
                    ):
                        traced.add(node.name)
                elif _is_trace_wrapper(_dotted(dec)):
                    traced.add(node.name)
    return traced


def _shape_ref(node: ast.AST, params: set[str]) -> str | None:
    """Param whose ``.shape``/``.ndim``/``.size``/``len()`` ``node``
    reads, if any."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
            "shape",
            "ndim",
            "size",
        ):
            base = sub.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Name) and base.id in params:
                return base.id
        if (
            isinstance(sub, ast.Call)
            and _dotted(sub.func) == "len"
            and sub.args
            and isinstance(sub.args[0], ast.Name)
            and sub.args[0].id in params
        ):
            return sub.args[0].id
    return None


def _value_branch_ref(test: ast.AST, params: set[str]) -> str | None:
    """Param used as a runtime truth value / numeric comparison in a
    branch test (``if x:``, ``while x > 0:``).  Comparisons against
    string constants and ``is None`` checks are static config, not
    traced-value branches."""
    if isinstance(test, ast.Name) and test.id in params:
        return test.id
    if isinstance(test, ast.UnaryOp):
        return _value_branch_ref(test.operand, params)
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            hit = _value_branch_ref(v, params)
            if hit:
                return hit
        return None
    if isinstance(test, ast.Compare):
        sides = [test.left, *test.comparators]
        names = [
            s.id for s in sides if isinstance(s, ast.Name) and s.id in params
        ]
        if not names:
            return None
        static = any(
            isinstance(s, ast.Constant)
            and (s.value is None or isinstance(s.value, str))
            for s in sides
        )
        if static or any(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return None
        return names[0]
    return None


def _check_recompile(
    mod: SourceModule, func: ast.AST, traced: set[str], report: Report
) -> None:
    if func.name not in traced:
        return
    params = {
        a.arg
        for a in [
            *func.args.posonlyargs,
            *func.args.args,
            *func.args.kwonlyargs,
        ]
        if a.arg not in ("self", "cls")
    }
    if not params:
        return
    for node in _own_nodes(func):
        if isinstance(node, (ast.If, ast.While)):
            hit = _value_branch_ref(node.test, params)
            kind = "value"
            if hit is None:
                hit = _shape_ref(node.test, params)
                kind = "shape"
            if hit:
                emit(
                    report,
                    mod,
                    "PWD602",
                    node.lineno,
                    f"traced function {func.name!r} branches on runtime "
                    f"{kind} of parameter {hit!r} — trace error or "
                    "per-shape recompile; pad to bucketed shapes instead",
                )
        elif isinstance(node, ast.For):
            it = node.iter
            if isinstance(it, ast.Call) and _dotted(it.func) == "range":
                hit = None
                for arg in it.args:
                    if isinstance(arg, ast.Name) and arg.id in params:
                        hit = arg.id
                    hit = hit or _shape_ref(arg, params)
                if hit:
                    emit(
                        report,
                        mod,
                        "PWD602",
                        node.lineno,
                        f"traced function {func.name!r} unrolls a Python "
                        f"loop bounded by parameter {hit!r} — recompiles "
                        "per bound; use lax.fori_loop/scan or a fixed "
                        "bucket",
                    )


# -- PWD603: uncounted transfer in engine/ ---------------------------------

_UPLOAD_CALLS = ("device_put",)
_UPLOAD_DOTTED = {"jnp.asarray", "jnp.array", "jax.numpy.asarray"}


def _in_engine(mod: SourceModule) -> bool:
    rel = mod.rel.replace("\\", "/")
    return "/engine/" in rel or rel.startswith("engine/")


def _local_func_map(mod: SourceModule) -> dict[str, ast.AST]:
    return {f.name: f for f, _cls in _all_funcs(mod.tree)}


def _touches_ledger(
    func: ast.AST, local: dict[str, ast.AST], depth: int = 0
) -> bool:
    for call in _calls(func):
        name = _call_name(call)
        if name in _LEDGER_CALLS:
            return True
        if depth < 1 and name in local and local[name] is not func:
            if _touches_ledger(local[name], local, depth + 1):
                return True
    return False


def _check_uncounted_transfer(
    mod: SourceModule,
    func: ast.AST,
    traced: set[str],
    local: dict[str, ast.AST],
    report: Report,
) -> None:
    if not _in_engine(mod) or func.name in traced:
        return
    sites: list[tuple[int, str]] = []
    dev = _device_vars(func)
    for call in _calls(func):
        dotted = _dotted(call.func)
        last = dotted.rsplit(".", 1)[-1]
        if last in _UPLOAD_CALLS or dotted in _UPLOAD_DOTTED:
            sites.append((call.lineno, f"{dotted or last}() upload"))
        elif dotted in ("np.asarray", "numpy.asarray") and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Name) and arg.id in dev:
                sites.append(
                    (call.lineno, f"{dotted}({arg.id}) materialization")
                )
            elif isinstance(arg, ast.Attribute) and "dev" in arg.attr.lower():
                sites.append(
                    (
                        call.lineno,
                        f"{dotted}(.{arg.attr}) materialization",
                    )
                )
    if not sites:
        return
    if _touches_ledger(func, local):
        return
    for line, what in sites:
        emit(
            report,
            mod,
            "PWD603",
            line,
            f"{what} in {func.name!r} without a pathway_device_transfer_* "
            "ledger increment (record_h2d/record_d2h) in the same "
            "function — transfers must be counted in BOTH modes",
        )


# -- PWD604: partial push on decline/except paths --------------------------

_MATERIALIZE_MARKERS = ("materialize", "asarray", "fetch", "to_numpy", "host")


def _delivery_scope(mod: SourceModule, func: ast.AST) -> bool:
    rel = mod.rel.replace("\\", "/").lower()
    if "exchange" in rel or "residency" in rel:
        return True
    low = func.name.lower()
    return "deliver" in low or "push" in low


def _stmt_calls(stmt: ast.stmt) -> list[ast.Call]:
    out = []
    for node in ast.walk(stmt):
        if isinstance(node, _SCOPES):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
    return out


def _is_push_call(call: ast.Call) -> bool:
    name = _call_name(call).lower()
    return name == "push" or "deliver" in name


def _is_materialize_call(call: ast.Call) -> bool:
    name = _call_name(call).lower()
    return any(m in name for m in _MATERIALIZE_MARKERS)


def _is_decline_stmt(stmt: ast.stmt) -> bool:
    """``STATS["declined_*"] += 1`` / ``.inc()`` on a declined counter."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Subscript) and isinstance(
            node.slice, ast.Constant
        ):
            if (
                isinstance(node.slice.value, str)
                and "declin" in node.slice.value
            ):
                return True
        if isinstance(node, ast.Attribute) and "declin" in node.attr.lower():
            return True
    return False


def _scan_block(
    mod: SourceModule,
    func: ast.AST,
    block: list[ast.stmt],
    armed: bool,
    why: str,
    report: Report,
) -> None:
    """Walk ``block`` statement-by-statement; once ``armed`` (decline or
    except path), a push/deliver before any whole-buffer materialization
    is a PWD604."""
    materialized = False
    for stmt in block:
        if isinstance(stmt, ast.Try):
            _scan_block(mod, func, stmt.body, armed, why, report)
            for handler in stmt.handlers:
                _scan_block(
                    mod, func, handler.body, True, "except path", report
                )
            _scan_block(mod, func, stmt.finalbody, armed, why, report)
            continue
        if isinstance(stmt, (ast.If, ast.While, ast.For, ast.With)):
            for sub in (
                getattr(stmt, "body", []),
                getattr(stmt, "orelse", []),
            ):
                _scan_block(mod, func, sub, armed, why, report)
            continue
        for call in _stmt_calls(stmt):
            if _is_materialize_call(call):
                materialized = True
            elif armed and not materialized and _is_push_call(call):
                emit(
                    report,
                    mod,
                    "PWD604",
                    call.lineno,
                    f"{func.name!r} reaches {_call_name(call)}() on a "
                    f"{why} before whole-buffer materialization — "
                    "declines must materialize whole or push nothing",
                )
        if not armed and _is_decline_stmt(stmt):
            armed, why = True, "decline path"


def _check_partial_push(
    mod: SourceModule, func: ast.AST, report: Report
) -> None:
    if not _delivery_scope(mod, func):
        return
    _scan_block(mod, func, func.body, False, "", report)


# -- PWD605: residency leak ------------------------------------------------

_RESIDENT_CLASS_MARKERS = ("resident", "devicebatch")
_REGISTRY_NAME_MARKERS = ("live", "resident", "handle", "staged")


def _registers_instances(cls: ast.ClassDef) -> bool:
    """Does any method of ``cls`` add instances to a live-set registry
    (``_LIVE_RESIDENT.add(self)`` style) or call a register helper?"""
    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for call in _calls(stmt):
            name = _call_name(call).lower()
            if name == "add" and isinstance(call.func, ast.Attribute):
                holder = _dotted(call.func.value).lower()
                if any(m in holder for m in _REGISTRY_NAME_MARKERS):
                    return True
            if "register" in name or "stage" in name:
                return True
    return False


def _resident_classes(
    modules: list[SourceModule],
) -> dict[str, bool]:
    """class name -> registers-for-decay, for device-resident classes."""
    out: dict[str, bool] = {}
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and any(
                m in node.name.lower() for m in _RESIDENT_CLASS_MARKERS
            ):
                out[node.name] = out.get(node.name, False) or (
                    _registers_instances(node)
                )
    return out


def _check_residency_leak(
    mod: SourceModule,
    func: ast.AST,
    resident_classes: dict[str, bool],
    report: Report,
) -> None:
    site_registers = False
    sites: list[tuple[int, str]] = []
    for call in _calls(func):
        name = _call_name(call)
        low = name.lower()
        if name in resident_classes:
            if not resident_classes[name]:
                sites.append((call.lineno, name))
        elif low == "add" and isinstance(call.func, ast.Attribute):
            holder = _dotted(call.func.value).lower()
            if any(m in holder for m in _REGISTRY_NAME_MARKERS):
                site_registers = True
        elif "register" in low or "stage_device" in low:
            site_registers = True
    if site_registers:
        return
    for line, cls in sites:
        emit(
            report,
            mod,
            "PWD605",
            line,
            f"{func.name!r} constructs {cls} but neither the class nor "
            "the construction site registers it for "
            "decay_resident_batches/drain_until retirement — resident "
            "batches would outlive the commit boundary",
        )


# -- PWD606: flag-liveness violation ---------------------------------------


def _env_flag(call_or_sub: ast.AST) -> str | None:
    """Flag name if the node reads an env var with a constant key."""
    if isinstance(call_or_sub, ast.Call):
        dotted = _dotted(call_or_sub.func)
        if dotted.endswith("environ.get") or dotted.endswith("getenv"):
            if call_or_sub.args and isinstance(
                call_or_sub.args[0], ast.Constant
            ):
                v = call_or_sub.args[0].value
                return v if isinstance(v, str) else None
    if isinstance(call_or_sub, ast.Subscript):
        if _dotted(call_or_sub.value).endswith("environ") and isinstance(
            call_or_sub.slice, ast.Constant
        ):
            v = call_or_sub.slice.value
            return v if isinstance(v, str) else None
    return None


def _check_flag_liveness(mod: SourceModule, report: Report) -> None:
    def scan(stmts: list[ast.stmt], scope: str) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.ClassDef):
                scan(stmt.body, f"class {stmt.name}")
                continue
            for node in ast.walk(stmt):
                if isinstance(node, _SCOPES):
                    continue
                flag = _env_flag(node)
                if flag and flag in LIVE_FLAGS:
                    emit(
                        report,
                        mod,
                        "PWD606",
                        node.lineno,
                        f"live-per-call flag {flag} read at {scope} scope "
                        "— cached at import, so runtime flips are "
                        "silently ignored; re-read it inside the call "
                        "path (see analysis/flags.py)",
                    )

    scan(mod.tree.body, "module")


# -- PWD607: metric-family discipline --------------------------------------

_REG_METHODS = {"counter", "gauge", "histogram"}
_USE_METHODS = {"inc", "observe", "set", "labels"}
_NON_LABEL_KWARGS = {"help", "buckets", "initial", "unit"}


@dataclass
class _Registration:
    mod: SourceModule
    line: int
    kind: str
    labels: frozenset[str]


@dataclass
class _MetricIndex:
    families: dict[str, list[_Registration]] = field(default_factory=dict)


def _collect_metrics(modules: list[SourceModule]) -> _MetricIndex:
    idx = _MetricIndex()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            last = dotted.rsplit(".", 1)[-1]
            family = None
            labels: frozenset[str] = frozenset()
            if (
                last in _REG_METHODS
                and "registry" in dotted.lower()
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("pathway_")
            ):
                family = node.args[0].value
                labels = frozenset(
                    kw.arg
                    for kw in node.keywords
                    if kw.arg and kw.arg not in _NON_LABEL_KWARGS
                )
            elif (
                last == "MirroredCounterDict"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("pathway_")
            ):
                family = node.args[0].value
                if len(node.args) > 1 and isinstance(
                    node.args[1], ast.Constant
                ):
                    labels = frozenset({str(node.args[1].value)})
            if family is not None:
                idx.families.setdefault(family, []).append(
                    _Registration(mod, node.lineno, last, labels)
                )
    return idx


def _check_metric_families(
    modules: list[SourceModule], idx: _MetricIndex, report: Report
) -> None:
    for family, regs in sorted(idx.families.items()):
        base = regs[0]
        for reg in regs[1:]:
            if reg.labels != base.labels:
                emit(
                    report,
                    reg.mod,
                    "PWD607",
                    reg.line,
                    f"metric family {family!r} registered with labels "
                    f"{sorted(reg.labels)} but first registered at "
                    f"{base.mod.rel}:{base.line} with "
                    f"{sorted(base.labels)} — label sets must agree",
                )
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            last = dotted.rsplit(".", 1)[-1]
            if (
                last in _USE_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("pathway_")
                and node.args[0].value not in idx.families
            ):
                emit(
                    report,
                    mod,
                    "PWD607",
                    node.lineno,
                    f"metric family {node.args[0].value!r} used at an "
                    f"increment site (.{last}) but never registered on "
                    "the metrics registry in the analyzed set",
                )


# -- driver ----------------------------------------------------------------


def run_pass(modules: list[SourceModule], report: Report) -> None:
    resident_classes = _resident_classes(modules)
    metric_idx = _collect_metrics(modules)
    for mod in modules:
        traced = _traced_names(mod)
        local = _local_func_map(mod)
        _check_flag_liveness(mod, report)
        for func, _cls in _all_funcs(mod.tree):
            _check_hot_sync(mod, func, report)
            _check_recompile(mod, func, traced, report)
            _check_uncounted_transfer(mod, func, traced, local, report)
            _check_partial_push(mod, func, report)
            _check_residency_leak(mod, func, resident_classes, report)
    _check_metric_families(modules, metric_idx, report)
