"""Pass 2 — dead-column and unused-operator detection.

Backward liveness over the Node DAG: starting from the observation roots
(SubscribeNode sinks; in sink-less engine graphs, every terminal node),
each node kind maps the set of *used output columns* to the set of input
columns it must read to produce them.  A column no consumer ever reads is
dead — the projection-pushdown report.

Severity policy: a dead column on a *source* (StaticSource/InputSession)
is a warning — the program ingests data it never looks at; dead columns on
intermediate operators are info-level (they are what a projection-pushdown
optimisation would elide, not user-visible waste).  Operators with no
consumers at all (in a graph that has sinks) are flagged once as unused
instead of per-column.
"""

from __future__ import annotations

from pathway_tpu.analysis.findings import Finding, Report, Severity
from pathway_tpu.engine import expression as ex
from pathway_tpu.engine import graph as g


def expr_refs(expr: ex.EngineExpression, out: set[int] | None = None) -> set[int]:
    """All input column indices an expression tree reads."""
    if out is None:
        out = set()
    if isinstance(expr, ex.ColumnRef):
        out.add(expr.index)
        return out
    children: list[ex.EngineExpression] = []
    if isinstance(expr, ex.Binary):
        children = [expr.left, expr.right]
    elif isinstance(expr, ex.Unary):
        children = [expr.arg]
    elif isinstance(expr, (ex.BooleanChain, ex.MakeTuple, ex.Coalesce)):
        children = list(expr.args)
    elif isinstance(expr, ex.IfElse):
        children = [expr.cond, expr.then, expr.otherwise]
    elif isinstance(expr, (ex.IsNone, ex.Unwrap)):
        children = [expr.arg]
    elif isinstance(expr, ex.Require):
        children = [expr.value, *expr.deps]
    elif isinstance(expr, (ex.SequenceGet, ex.JsonGet)):
        children = [expr.arg, expr.index]
        if expr.default is not None:
            children.append(expr.default)
    elif isinstance(expr, (ex.Cast, ex.Convert)):
        children = [expr.arg]
    elif isinstance(expr, ex.FillError):
        children = [expr.arg, expr.fallback]
    elif isinstance(expr, ex.Apply):
        children = list(expr.args)
    elif isinstance(expr, ex.PointerFrom):
        children = list(expr.args)
        if expr.instance is not None:
            children.append(expr.instance)
    for child in children:
        expr_refs(child, out)
    return out


def _all(node: g.Node) -> set[int]:
    return set(range(node.arity))


def input_needs(node: g.Node, used: set[int]) -> list[set[int]]:
    """Input columns (one set per input port) ``node`` reads to produce the
    ``used`` subset of its own output columns."""
    from pathway_tpu.engine import temporal as t

    if isinstance(node, g.ExpressionNode):
        need: set[int] = set()
        for i in used:
            if i < len(node.expressions):
                expr_refs(node.expressions[i], need)
        return [need]
    if isinstance(node, g.BatchApplyNode):
        return [set(node.arg_cols)]
    if isinstance(node, g.FilterNode):
        return [used | {node.condition_col}]
    if isinstance(node, g.ConcatNode):
        return [set(used) for _ in node.inputs]
    if isinstance(node, g.ReindexNode):
        return [used | {node.key_col}]
    if isinstance(node, g.KeyFilterNode):
        # the extra inputs contribute keys only, never column values
        return [set(used)] + [set() for _ in node.inputs[1:]]
    if isinstance(node, (g.OverrideUniverseNode, g._RemoveErrorsNode)):
        if isinstance(node, g._RemoveErrorsNode):
            return [_all(node.inputs[0])]  # is_error() scans every value
        return [set(used)]
    if isinstance(node, g.ZipNode):
        out: list[set[int]] = []
        offset = 0
        for inp in node.inputs:
            out.append(
                {i - offset for i in used if offset <= i < offset + inp.arity}
            )
            offset += inp.arity
        return out
    if isinstance(node, g.JoinNode):
        la = node.inputs[0].arity
        left = {i for i in used if i < la} | set(node.left_on)
        right = {i - la for i in used if i >= la} | set(node.right_on)
        if node.id_spec is not None and node.id_spec[1] is not None:
            side, col = node.id_spec
            (left if side == "left" else right).add(col)
        return [left, right]
    if isinstance(node, g.GroupbyNode):
        need = set(node.by_cols)
        nb = len(node.by_cols)
        for j, (_reducer, arg_cols) in enumerate(node.reducers):
            if nb + j in used:
                need |= set(arg_cols)
        return [need]
    if isinstance(node, g.DeduplicateNode):
        return [used | {node.value_col} | set(node.instance_cols)]
    if isinstance(node, g.FlattenNode):
        src_arity = node.inputs[0].arity
        need = {i for i in used if i < src_arity}
        need.add(node.flat_col)
        return [need]
    if isinstance(node, g.SortNode):
        need = {node.key_col}
        if node.instance_col is not None:
            need.add(node.instance_col)
        return [need]
    if isinstance(node, g.IxNode):
        return [{node.key_col}, set(used)]
    if isinstance(node, g.UpdateRowsNode):
        return [set(used), set(used)]
    if isinstance(node, g.UpdateCellsNode):
        upd = {
            node.update_cols[i]
            for i in used
            if i < len(node.update_cols) and node.update_cols[i] >= 0
        }
        return [set(used), upd]
    if isinstance(node, (g.SubscribeNode, g.ErrorLogNode)):
        return [_all(inp) for inp in node.inputs]
    if isinstance(node, (t.BufferNode, t.FreezeNode)):
        return [used | {node.threshold_col, node.time_col}]
    if isinstance(node, t.ForgetNode):
        src_arity = node.inputs[0].arity
        need = {i for i in used if i < src_arity}
        return [need | {node.threshold_col, node.time_col}]
    if isinstance(node, t.SessionAssignNode):
        src_arity = node.inputs[0].arity
        need = {i for i in used if i < src_arity} | {node.time_col}
        if node.instance_col is not None:
            need.add(node.instance_col)
        return [need]
    if isinstance(node, (t.IntervalJoinNode, t.AsofJoinNode)):
        la = node.inputs[0].arity
        left = {i for i in used if i < la} | {node.lt}
        right = {i - la for i in used if i >= la} | {node.rt}
        if node.li is not None:
            left.add(node.li)
        if node.ri is not None:
            right.add(node.ri)
        return [left, right]
    if isinstance(node, t.AsofNowJoinNode):
        la = node.inputs[0].arity
        left = {i for i in used if i < la} | set(node.left_on)
        right = {i - la for i in used if i >= la} | set(node.right_on)
        return [left, right]
    if isinstance(node, t.GradualBroadcastNode):
        src_arity = node.inputs[0].arity
        return [{i for i in used if i < src_arity}, {0, 1, 2}]
    # unknown / opaque kinds (Iterate, Recompute, ExternalIndex, custom):
    # assume every input column is read
    return [_all(inp) for inp in node.inputs]


def run_pass(scope: g.Scope, report: Report) -> dict[int, set[int]]:
    """Backward liveness; returns node index -> used output columns."""
    has_sinks = any(isinstance(n, g.SubscribeNode) for n in scope.nodes)
    used: dict[int, set[int]] = {n.index: set() for n in scope.nodes}

    for node in reversed(scope.nodes):
        if isinstance(node, (g.SubscribeNode, g.ErrorLogNode)):
            used[node.index] = _all(node)
        elif not node.consumers and not has_sinks:
            # engine-level graph driven by direct state reads (bench,
            # engine tests): terminal state is the observable output
            used[node.index] = _all(node)
        needs = input_needs(node, used[node.index])
        for port, inp in enumerate(node.inputs):
            if port < len(needs):
                used[inp.index] |= needs[port]

    for node in scope.nodes:
        if isinstance(node, (g.SubscribeNode, g.ErrorLogNode)):
            continue
        if not node.consumers:
            if has_sinks:
                report.add(
                    Finding(
                        code="PWA102",
                        message=(
                            "operator output is never consumed by any sink "
                            "or downstream operator"
                        ),
                        node_index=node.index,
                        node_name=node.name,
                        severity=Severity.WARNING,
                        trace=getattr(node, "trace", None) or None,
                    )
                )
            continue
        dead = sorted(set(range(node.arity)) - used[node.index])
        if not dead:
            continue
        is_source = isinstance(node, (g.StaticSource, g.InputSession))
        severity = Severity.WARNING if is_source else Severity.INFO
        what = (
            "ingested but never read — drop it at the source"
            if is_source
            else "computed but never read — a projection pushdown would "
            "elide it"
        )
        for col in dead:
            report.add(
                Finding(
                    code="PWA101",
                    message=f"column is {what}",
                    node_index=node.index,
                    node_name=node.name,
                    severity=severity,
                    column=col,
                    trace=getattr(node, "trace", None) or None,
                )
            )
    return used
