"""Pass 3 — shard-preservation analysis / exchange-redundancy report.

Models how each operator's *output* batches are partitioned across workers
under sharded execution (``engine/sharded.py`` delivers every producer →
consumer edge through an exchange governed by ``partition_rule``).  The
output partitioning of a node is a set of specs, each the normalized form
of a partition rule:

- ``("key",)``       — rows live on ``_shard_of(row_key)``
- ``("cols", cols)`` — rows live on ``_shard_of(tuple(row[c] for c in cols))``
- ``("col", c)``     — rows live on ``_shard_of(row[c])``

An exchange into a consumer whose ``partition_rule`` is already in the
producer's out-spec set provably moves no rows — flagged ``PWA201`` so an
exchange-elision pass (or a human) can act on it, cross-checkable at
runtime against ``EXCHANGE_STATS`` and ``native.hit_counts()``.

Soundness notes (why the transfer functions below are what they are):

- Groupby output keys are ``hash_values(by_vals, salt=gkey_salt)`` while
  the exchange hashes with ``salt=b"shard"`` — so groupby output is *not*
  key-partitioned, only cols-partitioned on its leading by-columns.
- Join output carries the join-key values on both sides at known
  positions, and matched rows hash identically through either side's
  spec, so both specs hold.
- A node whose arrival rule is ``("pin",)`` emits everything from worker
  0; no partitioning property survives it.
"""

from __future__ import annotations

from pathway_tpu.analysis.findings import Finding, Report, Severity
from pathway_tpu.engine import graph as g
from pathway_tpu.engine.sharded import partition_rule

Spec = tuple


def _norm(rule: tuple) -> Spec:
    if rule[0] == "cols":
        return ("cols", tuple(rule[1]))
    return tuple(rule)


def _spec_str(spec: Spec) -> str:
    if spec[0] == "key":
        return "by row key"
    if spec[0] == "cols":
        return f"by columns {list(spec[1])}"
    return f"by column {spec[1]}"


def _passthrough(node: g.Node) -> bool:
    """Same keys, same column positions in = out."""
    return isinstance(
        node,
        (
            g.FilterNode,
            g.KeyFilterNode,
            g.OverrideUniverseNode,
            g._RemoveErrorsNode,
            g.DeduplicateNode,
        ),
    )


def out_specs(node: g.Node) -> set[Spec]:
    """Partitioning properties of ``node``'s output batches."""
    arrival = _norm(partition_rule(node, 0))
    if arrival[0] == "pin":
        return set()
    if isinstance(node, (g.StaticSource, g.InputSession)):
        # sources are read whole on worker 0 and enter the exchange
        # unpartitioned (sharded.py _route_source)
        return set()
    if isinstance(node, g.GroupbyNode):
        # output rows land on the worker owning their by-values, and the
        # by-values are the leading output columns
        return {("cols", tuple(range(len(node.by_cols))))}
    if isinstance(node, g.JoinNode):
        la = node.inputs[0].arity
        specs = {("cols", tuple(node.left_on))}
        specs.add(("cols", tuple(la + c for c in node.right_on)))
        if node.kind != g.JoinKind.INNER:
            # padded (unmatched) rows carry None in the missing side's key
            # columns yet still live on the surviving side's worker — only
            # the surviving side's spec holds
            specs = (
                {("cols", tuple(node.left_on))}
                if node.kind == g.JoinKind.LEFT
                else {("cols", tuple(la + c for c in node.right_on))}
                if node.kind == g.JoinKind.RIGHT
                else set()
            )
        return specs
    if _passthrough(node):
        return {arrival}
    if isinstance(
        node,
        (
            g.ExpressionNode,
            g.BatchApplyNode,
            g.ConcatNode,
            g.ZipNode,
            g.UpdateRowsNode,
            g.UpdateCellsNode,
        ),
    ):
        # keys are preserved; column layout changes, so only a key-based
        # arrival property survives
        return {arrival} if arrival == ("key",) else set()
    # rekeying / lookup / unknown kinds: nothing provable
    return set()


def redundant_edges(scope: g.Scope) -> list[tuple[int, int, int, Spec]]:
    """Every exchange edge that provably moves no rows, as
    ``(producer_index, consumer_index, port, rule)`` tuples.

    This is both the PWA201 finding set and the exchange-elision oracle
    consumed by ``pathway_tpu.optimize`` — one derivation, so the
    analyzer and the rewriter can never disagree.
    """
    specs: dict[int, set[Spec]] = {
        node.index: out_specs(node) for node in scope.nodes
    }
    edges: list[tuple[int, int, int, Spec]] = []
    for node in scope.nodes:
        produced = specs[node.index]
        if not produced:
            continue
        for consumer, port in node.consumers:
            rule = _norm(partition_rule(consumer, port))
            if rule[0] == "pin":
                continue
            if rule in produced:
                edges.append((node.index, consumer.index, port, rule))
    return edges


def run_pass(scope: g.Scope, report: Report) -> None:
    from pathway_tpu.engine import temporal as t
    from pathway_tpu.engine.graph import RecomputeNode
    from pathway_tpu.engine.iterate import IterateNode

    pinned_kinds = (
        IterateNode,
        RecomputeNode,
        t.BufferNode,
        t.ForgetNode,
        t.FreezeNode,
        t.SessionAssignNode,
        t.IntervalJoinNode,
        t.AsofJoinNode,
        t.AsofNowJoinNode,
        t.GradualBroadcastNode,
    )
    try:
        from pathway_tpu.engine.external_index import ExternalIndexNode

        pinned_kinds = pinned_kinds + (ExternalIndexNode,)
    except ImportError:
        pass

    for node in scope.nodes:
        if isinstance(node, pinned_kinds):
            report.add(
                Finding(
                    code="PWA202",
                    message=(
                        "globally-stateful operator funnels the stream "
                        "through worker 0 under sharded execution"
                    ),
                    node_index=node.index,
                    node_name=node.name,
                    severity=Severity.INFO,
                    trace=getattr(node, "trace", None) or None,
                )
            )

    for prod, cons, port, rule in redundant_edges(scope):
        node = scope.nodes[prod]
        consumer = scope.nodes[cons]
        report.add(
            Finding(
                code="PWA201",
                message=(
                    f"exchange into {consumer.name}#{consumer.index} "
                    f"(port {port}) is provably redundant: rows are "
                    f"already partitioned {_spec_str(rule)} "
                    "(cross-check: EXCHANGE_STATS / "
                    "native.hit_counts())"
                ),
                node_index=node.index,
                node_name=node.name,
                severity=Severity.INFO,
                trace=getattr(node, "trace", None) or None,
            )
        )
