"""Concurrent HTTP query front over the snapshot store.

The read-plane counterpart of the monitoring endpoint and the same port
scheme one block up: each process serves its own shard's snapshots on
``21000 + PATHWAY_PROCESS_ID`` (``PATHWAY_TPU_SERVING_PORT_BASE``
overrides the base), loopback only.  Three mechanisms keep thousands of
concurrent queries off the dataflow's back:

- **Admission control**: accepted connections enter a bounded queue
  drained by a fixed thread pool; when the queue is full the connection
  is shed immediately with ``503`` + ``Retry-After`` (never queued
  behind work that cannot be served in time), and once a request is
  admitted it is always answered — possibly from a stale snapshot,
  never with a 5xx.
- **Micro-batching**: concurrently-arriving KNN queries are packed into
  one snapshot ``search`` call, sized by the PR-9
  ``AdaptiveBatchController`` (the same controller that sizes device
  update batches, so serving batches track device backpressure) within
  a short packing window (``PATHWAY_TPU_SERVING_BATCH_WINDOW_MS``).
- **Snapshot reads**: every answer comes from a refcounted immutable
  :class:`~pathway_tpu.serving.snapshot.ReadSnapshot` — queries touch
  no operator state and hold no scheduler lock.

Endpoints (all JSON):

- ``GET  /serving/health``  — liveness + snapshot seq/commit/staleness
- ``GET  /serving/stats``   — request/shed counters, latency quantiles
- ``POST /serving/query``   — ``{"vector": [...] | "vectors": [[...]],
  "k": 10}`` -> KNN hits from the newest snapshot
- ``POST /serving/lookup``  — ``{"keys": [...]}`` -> operator rows by
  repr-stringified key (point reads on groupby/join state)
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time as _time
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing
from pathway_tpu.serving import result_cache as _result_cache
from pathway_tpu.serving import snapshot as _snapshot
from pathway_tpu.serving.snapshot import StaleReadError

__all__ = ["QueryServer", "BASE_PORT", "serving_port"]

BASE_PORT = 21000

_LAT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5,
)

_REQS = {
    ep: _metrics.REGISTRY.counter(
        "pathway_serving_requests_total",
        "admitted serving requests by endpoint",
        endpoint=ep,
    )
    for ep in ("query", "lookup", "health", "stats", "other")
}
_SHED = _metrics.REGISTRY.counter(
    "pathway_serving_shed_total",
    "connections shed at admission (503 + Retry-After)",
)
_LATENCY = _metrics.REGISTRY.histogram(
    "pathway_serving_latency_seconds",
    "per-request serving latency (admission to response flush)",
    buckets=_LAT_BUCKETS,
)
_BATCHED = _metrics.REGISTRY.histogram(
    "pathway_serving_batch_queries",
    "KNN queries packed per snapshot search dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
_EMPTY = _metrics.REGISTRY.counter(
    "pathway_serving_no_snapshot_total",
    "admitted queries answered 200-with-empty because no snapshot exists yet",
)
_STALE = _metrics.REGISTRY.counter(
    "pathway_serving_stale_503_total",
    "admitted requests answered 503 because the store's freshest "
    "consistent view exceeded its staleness bound",
)

_started_wall: list[float] = []  # first QueryServer.start() in this process


def _collect_uptime():
    if _started_wall:
        yield (
            "pathway_serving_uptime_seconds",
            "gauge",
            "seconds since this process's query server started",
            {},
            _time.time() - _started_wall[0],
        )


_metrics.REGISTRY.register_collector(_collect_uptime)


def serving_port(process_id: int | None = None) -> int:
    base = int(os.environ.get("PATHWAY_TPU_SERVING_PORT_BASE", BASE_PORT))
    if process_id is None:
        process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    return base + process_id


def stamp_header_value(stamp) -> str:
    """Deterministic ``X-Pathway-Stamp`` value from a cache stamp
    (``(commit_time, seq-or-cut, fingerprint)``): the commit identity
    without the fingerprint, compact-JSON so a hit and a miss answered
    at the same stamp carry byte-identical headers."""
    try:
        return json.dumps(
            list(stamp[:2]), separators=(",", ":"), default=repr
        )
    except Exception:
        return repr(stamp)


def _suggested_batch() -> int:
    """Micro-batch capacity from the device pipeline's adaptive
    controller — when the device side is backpressured the controller
    grows its batches, and serving follows so queries amortize into
    fewer top_k dispatches."""
    try:
        from pathway_tpu.engine import device_pipeline as _dp

        return max(1, int(_dp.PIPELINE.controller.batch_size))
    except Exception:
        return 1024


class _MicroBatcher:
    """Packs concurrently-arriving KNN queries into one snapshot search."""

    def __init__(self, store: "_snapshot.SnapshotStore", window_s: float):
        self.store = store
        self.window_s = max(0.0, window_s)
        self._cv = threading.Condition()
        self._pending: list[dict] = []  # guarded-by: self._cv
        self._stop = False  # guarded-by: self._cv
        self._thread: threading.Thread | None = None
        self.dispatches = 0

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="pw-serving-batcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def submit(self, vectors: np.ndarray, k: int, timeout: float = 30.0):
        """Enqueue ``vectors`` ([n, dim]) and block until the batcher
        answers.  Returns ``(hits, snapshot_meta)``; hits is None only
        when no snapshot has ever been published."""
        item = {
            "vecs": vectors,
            "k": int(k),
            "event": threading.Event(),
            "hits": None,
            "meta": None,
            "error": None,
        }
        with self._cv:
            self._pending.append(item)
            self._cv.notify_all()
        if not item["event"].wait(timeout):
            raise TimeoutError("serving batcher did not answer in time")
        if item["error"] is not None:
            raise item["error"]
        return item["hits"], item["meta"]

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(0.25)
                if self._stop:
                    pending, self._pending = self._pending, []
                else:
                    # packing window: wait briefly for more arrivals, up
                    # to the controller-suggested batch capacity
                    cap = _suggested_batch()
                    deadline = _time.perf_counter() + self.window_s
                    while (
                        sum(len(i["vecs"]) for i in self._pending) < cap
                        and not self._stop
                    ):
                        left = deadline - _time.perf_counter()
                        if left <= 0:
                            break
                        self._cv.wait(left)
                    pending, self._pending = self._pending, []
            if not pending:
                if self._stop:
                    return
                continue
            self._dispatch(pending)
            if self._stop:
                with self._cv:
                    leftover, self._pending = self._pending, []
                if leftover:
                    self._dispatch(leftover)
                return

    def _dispatch(self, pending: list[dict]) -> None:
        t0 = _time.perf_counter()
        snap = None
        try:
            # inside the try: a raising store (a replica past its
            # staleness bound) must fail the waiters, not this thread
            snap = self.store.acquire_latest()
            t_pin = _time.perf_counter()
            n = sum(len(i["vecs"]) for i in pending)
            if snap is None:
                for item in pending:
                    item["hits"] = None
                    item["meta"] = None
                return
            max_k = max(i["k"] for i in pending)
            flat = [vec for item in pending for vec in item["vecs"]]
            try:
                results = snap.search(flat, max_k)
            except LookupError as exc:
                for item in pending:
                    item["error"] = exc
                return
            t_search = _time.perf_counter()
            meta = {
                "seq": snap.seq,
                "commit_time": snap.commit_time,
                "staleness_s": round(snap.staleness_s(), 6),
                # stripped by the handler before serialization: the
                # result cache only inserts when the snapshot actually
                # answered matches the stamp it keyed the lookup on
                "cache_stamp": snap.cache_stamp(),
                # stripped likewise: (name, cat, t0, t1, args) tuples the
                # handler replays into its request trace — the batcher
                # thread has no request context, the waiters do
                "_req_spans": [
                    (
                        # a ReplicaStore pin waits for a consistent cut;
                        # a plain SnapshotStore pin is a refcount bump
                        (
                            "cut-wait"
                            if hasattr(self.store, "lag_s")
                            else "snapshot-pin"
                        ),
                        "wait",
                        t0,
                        t_pin,
                        {"seq": snap.seq, "commit_time": snap.commit_time},
                    ),
                    (
                        "search",
                        "serving",
                        t_pin,
                        t_search,
                        {"queries": n, "k": max_k},
                    ),
                ],
            }
            self.dispatches += 1
            _BATCHED.observe_n(float(n), 1)
            pos = 0
            for item in pending:
                rows = results[pos : pos + len(item["vecs"])]
                item["hits"] = [r[: item["k"]] for r in rows]
                # a COPY per waiter: handlers pop cache_stamp/_req_spans
                # from their own meta, so concurrent batch-mates never
                # race on one shared dict
                item["meta"] = dict(meta)
                pos += len(item["vecs"])
            _tracing.TRACER.record_query(
                "knn-batch",
                t0,
                _time.perf_counter(),
                commit_time=snap.commit_time,
                queries=n,
                requests=len(pending),
                k=max_k,
            )
        except Exception as exc:  # noqa: BLE001 — fail the waiters, not the loop
            for item in pending:
                if item["error"] is None and item["hits"] is None:
                    item["error"] = exc
        finally:
            if snap is not None:
                snap.release()
            for item in pending:
                item["event"].set()


class _Handler(BaseHTTPRequestHandler):
    # default HTTP/1.0 + Connection: close — one bounded-pool turn per
    # connection, so admission control maps 1:1 to requests
    server_version = "PathwayServing/1.0"

    #: per-request trace context / wide-event state; handler instances
    #: are per-connection (HTTP/1.0 + close => per-request)
    _rctx = None
    _last_status = 0

    def log_message(self, fmt: str, *args) -> None:  # noqa: A003
        pass  # the metrics registry is the access log

    # -- helpers -------------------------------------------------------------

    def _json(
        self, code: int, payload: dict, headers: dict | None = None
    ) -> None:
        self._raw_json(code, json.dumps(payload).encode(), headers)

    def _raw_json(
        self, code: int, body: bytes, headers: dict | None = None
    ) -> None:
        """Send pre-serialized JSON bytes — the result-cache hit path
        writes the cached body verbatim, skipping re-serialization."""
        self._last_status = code
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        rctx = self._rctx
        if rctx is not None:
            if rctx.remote:
                # downstream hop: piggyback this hop's spans back to
                # the caller that owns the trace
                payload = _tracing.encode_spans(rctx.take_spans())
                if payload is not None:
                    self.send_header(_tracing.SPANS_HEADER, payload)
            else:
                # root: echo the trace id so clients/benches can join
                # the response to the exported trace
                self.send_header(_tracing.TRACE_HEADER, rctx.trace_id)
        self.end_headers()
        self.wfile.write(body)

    def _stale(self, exc: StaleReadError) -> None:
        _STALE.inc()
        _metrics.FLIGHT.record(
            "serving_stale_503",
            port=self.server.server_port,
            error=str(exc),
        )
        self._wide["refusal"] = "stale"
        self._json(
            503,
            {"error": str(exc), "stale": True},
            headers={"Retry-After": "1"},
        )

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    # -- endpoints -----------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        t0 = _time.perf_counter()
        try:
            if self.path.startswith("/serving/health"):
                _REQS["health"].inc()
                self._json(200, dict(self.server.store.stats(), ok=True))
            elif self.path.startswith("/serving/stats"):
                _REQS["stats"].inc()
                self._json(200, self.server.serving_stats())
            else:
                _REQS["other"].inc()
                self._json(404, {"error": f"unknown path {self.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            _LATENCY.observe(_time.perf_counter() - t0)

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        t0 = _time.perf_counter()
        self._wide = {}
        if self.path.startswith("/serving/query"):
            endpoint = "query"
        elif self.path.startswith("/serving/lookup"):
            endpoint = "lookup"
        else:
            endpoint = "other"
        tracer = _tracing.TRACER
        # a sampled upstream header wins (the root owns the sampling
        # decision); otherwise this hop is its own root candidate
        rctx = tracer.adopt_request(
            self.headers.get(_tracing.TRACE_HEADER), endpoint
        )
        if rctx is None and endpoint != "other":
            rctx = tracer.begin_request(endpoint)
        self._rctx = rctx
        if rctx is not None:
            admit = getattr(self.server, "_admit_local", None)
            enq = getattr(admit, "enq", None)
            deq = getattr(admit, "deq", None)
            if enq is not None and deq is not None and deq > enq:
                rctx.span("admission-queue", "wait", enq, deq)
        try:
            if endpoint == "query":
                _REQS["query"].inc()
                self._query(t0)
            elif endpoint == "lookup":
                _REQS["lookup"].inc()
                self._lookup(t0)
            else:
                _REQS["other"].inc()
                self._json(404, {"error": f"unknown path {self.path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except StaleReadError as exc:
            # a replica past its staleness bound: refuse loudly rather
            # than answer wrong — 503 + Retry-After, never a 5xx crash
            try:
                self._stale(exc)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (ValueError, KeyError, TypeError) as exc:
            # malformed request — a client error, not a serving failure
            try:
                self._json(400, {"error": repr(exc)})
            except (BrokenPipeError, ConnectionResetError):
                pass
        finally:
            dt = _time.perf_counter() - t0
            _LATENCY.observe(dt)
            if rctx is not None:
                _LATENCY.exemplar(dt, rctx.trace_id)
                # wide event BEFORE the context is torn down, so the
                # trace-id provider still sees it
                _metrics.REQUESTS.record(
                    endpoint=endpoint,
                    status=self._last_status,
                    port=self.server.server_port,
                    ns=int(dt * 1e9),
                    **self._wide,
                )
                tracer.end_request(
                    rctx, status=self._last_status, **self._wide
                )
            tracer.drop_request()

    def _query(self, t0: float) -> None:
        req = self._body()
        if "vectors" in req:
            vecs = np.asarray(req["vectors"], np.float32)
        else:
            vecs = np.asarray([req["vector"]], np.float32)
        if vecs.ndim != 2:
            raise ValueError("vector(s) must be rank-1 / rank-2")
        k = int(req.get("k", 10))
        key = self._cache_key(
            "query",
            vecs.tobytes() + b"|" + repr((vecs.shape, k)).encode(),
        )
        if key is not None:
            tc0 = _time.perf_counter()
            cached = _result_cache.CACHE.get(key)
            self._note_cache(
                "hit" if cached is not None else "miss", key[1], tc0
            )
            if cached is not None:
                # hot path: cached answers never touch the batcher or
                # pin a snapshot — serialized bytes straight back out
                self._raw_json(
                    200,
                    cached,
                    {
                        "X-Pathway-Cache": "hit",
                        "X-Pathway-Stamp": stamp_header_value(key[1]),
                    },
                )
                _result_cache.CACHE.observe_hit_latency(
                    _time.perf_counter() - t0
                )
                return
        hits, meta = self.server.batcher.submit(vecs, k)
        self._replay_batch_spans(meta)
        if hits is None:
            # admitted before the first commit: answer empty-but-valid
            # (stale by definition), never a 5xx
            _EMPTY.inc()
            self._json(
                200,
                {"hits": [[] for _ in range(len(vecs))], "snapshot": None},
                headers={"X-Pathway-Cache": "miss"},
            )
            return
        answered = meta.pop("cache_stamp", None)
        body = json.dumps(
            {
                "hits": [
                    [[repr(key_), score] for key_, score in row]
                    for row in hits
                ],
                "snapshot": meta,
            }
        ).encode()
        self._maybe_insert(key, answered, body)
        self._wide["commit_time"] = meta.get("commit_time")
        headers = {"X-Pathway-Cache": "miss"}
        if answered is not None:
            headers["X-Pathway-Stamp"] = stamp_header_value(answered)
        self._raw_json(200, body, headers)

    def _note_cache(self, disposition: str, stamp, t0: float) -> None:
        """Cache-disposition span + wide-event fields for one lookup."""
        self._wide["cache"] = disposition
        self._wide["stamp"] = repr(stamp[:2])
        rctx = self._rctx
        if rctx is not None:
            rctx.span(
                "result-cache",
                "serving",
                t0,
                _time.perf_counter(),
                disposition=disposition,
            )

    def _replay_batch_spans(self, meta: dict | None) -> None:
        """Pull the batcher's span tuples out of this waiter's meta copy
        and replay them into the request trace (the batcher thread has
        no request context; the handler thread does)."""
        spans = meta.pop("_req_spans", None) if meta else None
        rctx = self._rctx
        if rctx is not None and spans:
            for name, cat, s0, s1, sargs in spans:
                rctx.span(name, cat, s0, s1, **sargs)

    def _cache_key(self, endpoint: str, material: bytes):
        """Commit-stamped cache key, or None when caching is off or no
        snapshot exists yet.  The stamp embeds commit time, seq, and
        the rewrite fingerprint — invalidation by publication."""
        if not _result_cache.enabled():
            return None
        stamp = self.server.store.stamp()
        if stamp is None:
            return None
        # the port disambiguates servers sharing one process-wide cache
        # (in-process meshes/tests run several stores side by side)
        return (
            endpoint,
            stamp,
            _result_cache.query_digest(endpoint, material),
            self.server.server_port,
        )

    def _maybe_insert(self, key, answered_stamp, body: bytes) -> None:
        """Insert only when the snapshot that actually answered is the
        one the key was stamped with — a publication racing between the
        stamp peek and the dispatch must not be cached under the old
        stamp (its recompute would differ bit-for-bit)."""
        if key is None or answered_stamp is None:
            return
        if answered_stamp != key[1]:
            return
        _result_cache.CACHE.put(
            key, body, len(body), commit_time=answered_stamp[0]
        )

    def _lookup(self, t0: float | None = None) -> None:
        if t0 is None:
            t0 = _time.perf_counter()
        req = self._body()
        keys = [str(key) for key in req.get("keys", [])]
        node = req.get("node")
        key = self._cache_key(
            "lookup",
            json.dumps({"keys": keys, "node": node}, sort_keys=True).encode(),
        )
        if key is not None:
            tc0 = _time.perf_counter()
            cached = _result_cache.CACHE.get(key)
            self._note_cache(
                "hit" if cached is not None else "miss", key[1], tc0
            )
            if cached is not None:
                self._raw_json(
                    200,
                    cached,
                    {
                        "X-Pathway-Cache": "hit",
                        "X-Pathway-Stamp": stamp_header_value(key[1]),
                    },
                )
                _result_cache.CACHE.observe_hit_latency(
                    _time.perf_counter() - t0
                )
                return
        t_pin0 = _time.perf_counter()
        snap = self.server.store.acquire_latest()
        if snap is None:
            _EMPTY.inc()
            self._json(
                200,
                {"rows": {}, "snapshot": None},
                headers={"X-Pathway-Cache": "miss"},
            )
            return
        rctx = self._rctx
        if rctx is not None:
            rctx.span(
                (
                    "cut-wait"
                    if hasattr(self.server.store, "lag_s")
                    else "snapshot-pin"
                ),
                "wait",
                t_pin0,
                _time.perf_counter(),
                seq=snap.seq,
                commit_time=snap.commit_time,
            )
        try:
            t1 = _time.perf_counter()
            table = {repr(key_): row for key_, row in snap.table(node).items()}
            rows = (
                {key_: table.get(key_) for key_ in keys} if keys else table
            )
            meta = {
                "seq": snap.seq,
                "commit_time": snap.commit_time,
                "staleness_s": round(snap.staleness_s(), 6),
            }
            answered = snap.cache_stamp()
            t2 = _time.perf_counter()
            _tracing.TRACER.record_query(
                "table-lookup",
                t1,
                t2,
                commit_time=snap.commit_time,
                keys=len(keys),
            )
            if rctx is not None:
                rctx.span(
                    "table-lookup", "serving", t1, t2, keys=len(keys)
                )
        finally:
            snap.release()
        body = json.dumps({"rows": rows, "snapshot": meta}).encode()
        self._maybe_insert(key, answered, body)
        self._wide["commit_time"] = meta.get("commit_time")
        headers = {"X-Pathway-Cache": "miss"}
        if answered is not None:
            headers["X-Pathway-Stamp"] = stamp_header_value(answered)
        self._raw_json(200, body, headers)


class _BoundedHTTPServer(HTTPServer):
    """HTTP server with bounded-queue admission and a fixed worker pool.

    ``process_request`` (the accept-loop side) either enqueues the
    connection or sheds it with a raw 503 — it never blocks and never
    spawns a thread per connection, so a query flood degrades into fast
    503s instead of an unbounded thread pile-up."""

    allow_reuse_address = True
    daemon_threads = True
    # shedding is OUR bounded queue's job: a deep listen backlog keeps
    # the kernel from dropping SYNs under bursts (a dropped SYN costs
    # the client a ~1s retransmit, which would read as serving latency)
    request_queue_size = 512

    def __init__(
        self, addr, handler, store, batcher, queue_size: int, threads: int
    ) -> None:
        super().__init__(addr, handler)
        self.store = store
        self.batcher = batcher
        self.started_wall = _time.time()
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, queue_size))
        #: per-worker-thread admission timestamps (enq/deq perf stamps
        #: of the request the thread is currently handling) — read by
        #: the handler, which runs on the same pool thread
        self._admit_local = threading.local()
        self._pool_stop = False
        self._pool = [
            threading.Thread(
                target=self._worker, name=f"pw-serving-{i}", daemon=True
            )
            for i in range(max(1, threads))
        ]
        for t in self._pool:
            t.start()

    def process_request(self, request, client_address) -> None:
        try:
            self._queue.put_nowait(
                (request, client_address, _time.perf_counter())
            )
        except queue.Full:
            _SHED.inc()
            # shed before the headers are ever read, so no trace id can
            # exist for this connection — the wide event records the
            # refusal without one
            _metrics.FLIGHT.record(
                "serving_shed", port=self.server_port
            )
            _metrics.REQUESTS.record(
                endpoint="admission",
                status=503,
                port=self.server_port,
                refusal="shed",
            )
            try:
                request.sendall(
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Retry-After: 1\r\n"
                    b"Content-Length: 0\r\n"
                    b"Connection: close\r\n\r\n"
                )
            except OSError:
                pass
            self.shutdown_request(request)

    def _worker(self) -> None:
        # bounded get: a sentinel can be lost to a full queue during
        # shutdown, so the stop flag — not the sentinel — is what
        # guarantees this daemon exits
        while True:
            try:
                item = self._queue.get(timeout=0.25)
            except queue.Empty:
                if self._pool_stop:
                    return
                continue
            if item is None:
                return
            request, client_address, t_enq = item
            self._admit_local.enq = t_enq
            self._admit_local.deq = _time.perf_counter()
            try:
                self.finish_request(request, client_address)
            except Exception:  # noqa: BLE001 — one bad socket, not the pool
                pass
            finally:
                self.shutdown_request(request)

    def stop_pool(self) -> None:
        self._pool_stop = True
        for _ in self._pool:
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                break  # workers still exit via the stop flag
        for t in self._pool:
            t.join(timeout=2.0)

    def serving_stats(self) -> dict:
        uptime = max(1e-9, _time.time() - self.started_wall)
        requests = sum(c.value for c in _REQS.values())
        return {
            "uptime_s": round(uptime, 3),
            "requests": requests,
            "qps": round(requests / uptime, 2),
            "shed": _SHED.value,
            "no_snapshot": _EMPTY.value,
            "latency_ms": {
                "p50": round(_LATENCY.quantile(0.50) * 1000.0, 3),
                "p95": round(_LATENCY.quantile(0.95) * 1000.0, 3),
                "p99": round(_LATENCY.quantile(0.99) * 1000.0, 3),
                "count": _LATENCY.count,
            },
            "batch": {
                "dispatches": self.batcher.dispatches,
                "queries": _BATCHED.sum,
            },
            "stale_503": _STALE.value,
            "cache": _result_cache.CACHE.stats(),
            "snapshot": self.store.stats(),
        }


class QueryServer:
    """Lifecycle wrapper: bind, pump, stop.  One per process, started by
    ``pw.run`` when ``PATHWAY_TPU_SERVING=1`` (mirrors
    ``MonitoringHttpServer``)."""

    def __init__(
        self,
        store: "_snapshot.SnapshotStore" | None = None,
        port: int | None = None,
        queue_size: int | None = None,
        threads: int | None = None,
        batch_window_ms: float | None = None,
    ) -> None:
        self.store = store if store is not None else _snapshot.STORE
        self.port = port if port is not None else serving_port()
        if queue_size is None:
            queue_size = int(
                os.environ.get("PATHWAY_TPU_SERVING_QUEUE", "256")
            )
        if threads is None:
            threads = int(os.environ.get("PATHWAY_TPU_SERVING_THREADS", "8"))
        if batch_window_ms is None:
            batch_window_ms = float(
                os.environ.get("PATHWAY_TPU_SERVING_BATCH_WINDOW_MS", "2")
            )
        self.batcher = _MicroBatcher(self.store, batch_window_ms / 1000.0)
        self.httpd = _BoundedHTTPServer(
            ("127.0.0.1", self.port),
            _Handler,
            self.store,
            self.batcher,
            queue_size,
            threads,
        )
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "QueryServer":
        if not _started_wall:
            _started_wall.append(self.httpd.started_wall)
        self.batcher.start()
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="pw-serving-http",
            daemon=True,
        )
        self._thread.start()
        _metrics.FLIGHT.record("serving_start", port=self.port)
        return self

    def stop(self) -> None:
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd.stop_pool()
        finally:
            self.batcher.stop()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
        _metrics.FLIGHT.record("serving_stop", port=self.port)
