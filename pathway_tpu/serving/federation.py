"""Leader-side federation front: one read endpoint for the whole mesh.

Clients of the per-worker query plane (:mod:`pathway_tpu.serving.server`)
must know the mesh width, fan a query out to every worker's port, and
merge shard answers themselves.  The federation front — started on the
leader when ``PATHWAY_TPU_SERVING_FEDERATION=1`` — does that once for
everyone, on ``PATHWAY_TPU_FEDERATION_PORT`` (default 23000):

- ``POST /serving/query`` is scattered concurrently to every worker's
  QueryServer; per-query top-k lists are merged with **exactly** the
  stable-sort contract :meth:`ReadSnapshot.search` applies across its
  own shards (concatenate in worker order, stable-sort on descending
  score, truncate to k) — so a federated answer is bit-identical to a
  client-side fan-out merge at the same commits.
- ``POST /serving/lookup`` unions shard rows (workers partition the key
  space).
- Answers are stamped with the **minimum common commit** across the
  shard answers — the commit the merged view is consistent at.
- When read replicas are configured (``PATHWAY_TPU_REPLICAS``: a count,
  or a ``host:port`` list), queries round-robin across them first —
  each replica already holds the whole mesh's consistent cut, so a
  replica route costs one hop instead of a width-wide scatter, and
  query capacity scales with the replica pool instead of ingest width.
  A failing or stale replica falls back to the next, then to the
  worker scatter, so replica churn degrades latency, not availability.
- Scatter answers are cached in the shared commit-stamped
  :mod:`result cache <pathway_tpu.serving.result_cache>` under the full
  per-worker stamp vector; a background poller tracks the backends'
  current stamps so hot federated queries short-circuit without any
  fan-out at all.  Rollback invalidation rides the same store-truncate
  hook as the worker-level cache (the front lives in the leader
  process).

A partial scatter is never served: if any worker cannot answer, the
front degrades to replicas or a 503 + Retry-After — merged-but-missing-
a-shard rows would violate the bit-identical contract.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing
from pathway_tpu.serving import result_cache as _result_cache
from pathway_tpu.serving import server as _server
from pathway_tpu.serving.replica import parse_sources, replica_port

__all__ = [
    "FederationFront",
    "enabled",
    "federation_port",
    "replica_endpoints",
    "BASE_PORT",
]

BASE_PORT = 23000

_FED_REQS = {
    ep: _metrics.REGISTRY.counter(
        "pathway_serving_federation_requests_total",
        "federated read requests by endpoint",
        endpoint=ep,
    )
    for ep in ("query", "lookup", "health", "stats", "other")
}
_FED_ROUTE = {
    route: _metrics.REGISTRY.counter(
        "pathway_serving_federation_routes_total",
        "how federated queries were answered "
        "(cache/replica/scatter/unavailable)",
        route=route,
    )
    for route in ("cache", "replica", "scatter", "unavailable")
}
_FED_FANOUT = _metrics.REGISTRY.histogram(
    "pathway_serving_federation_fanout",
    "backend requests issued per federated query",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64),
)
_FED_LATENCY = _metrics.REGISTRY.histogram(
    "pathway_serving_federation_latency_seconds",
    "federated request latency (admission to response flush)",
    buckets=_server._LAT_BUCKETS,
)


def enabled() -> bool:
    return os.environ.get(
        "PATHWAY_TPU_SERVING_FEDERATION", "0"
    ).lower() in ("1", "true", "yes")


def federation_port() -> int:
    return int(os.environ.get("PATHWAY_TPU_FEDERATION_PORT", BASE_PORT))


def replica_endpoints() -> list[tuple[str, int]]:
    """``PATHWAY_TPU_REPLICAS``: a bare count N (replicas at the port
    scheme ``24000+i``) or an explicit ``host:port,host:port`` list."""
    spec = os.environ.get("PATHWAY_TPU_REPLICAS", "").strip()
    if not spec:
        return []
    try:
        count = int(spec)
    except ValueError:
        return parse_sources(spec)
    return [("127.0.0.1", replica_port(i)) for i in range(max(0, count))]


def _post_json(
    url: str,
    payload: dict,
    timeout: float,
    headers: dict | None = None,
) -> tuple[int, dict, Any]:
    """POST JSON; returns ``(status, body, response_headers)`` — the
    headers carry the trace-span piggyback on instrumented backends."""
    all_headers = {"Content-Type": "application/json"}
    if headers:
        all_headers.update(headers)
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers=all_headers,
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read() or b"{}")
        except ValueError:
            body = {}
        return exc.code, body, exc.headers


def _stamp_header(answered: tuple | None, meta: dict | None) -> str | None:
    """``X-Pathway-Stamp`` value for a federated answer: the full
    per-worker stamp vector when the scatter produced one (compact
    JSON, so a cache hit and a recompute at the same vector carry
    byte-identical headers), else the replica answer's commit
    identity."""
    if answered is not None:
        try:
            return json.dumps(
                list(answered), separators=(",", ":"), default=repr
            )
        except (TypeError, ValueError):
            return repr(answered)
    if meta and meta.get("commit_time") is not None:
        return json.dumps(
            [meta["commit_time"], meta.get("seq", 0)],
            separators=(",", ":"),
        )
    return None


def _get_json(url: str, timeout: float) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, {}


class FederationUnavailable(RuntimeError):
    """No route could produce a full-mesh answer right now (a worker is
    mid-restart and no replica has a fresh cut).  Mapped to 503 +
    Retry-After — the front never serves a partial merge."""


class _FederationHTTPServer(_server._BoundedHTTPServer):
    """Same bounded-queue admission as the worker servers; the handler
    talks to ``self.front`` instead of a local store."""

    front: "FederationFront" = None  # set right after construction

    def serving_stats(self) -> dict:
        return self.front.stats()


class _FedHandler(_server._Handler):
    # inherits _json/_raw_json/_body/_stale and the logging suppression

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        t0 = _time.perf_counter()
        try:
            path = self.path
            if "/health" in path:
                _FED_REQS["health"].inc()
                self._json(200, self.server.front.health())
            elif "/stats" in path:
                _FED_REQS["stats"].inc()
                self._json(200, self.server.front.stats())
            else:
                _FED_REQS["other"].inc()
                self._json(404, {"error": f"unknown path {path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            _FED_LATENCY.observe(_time.perf_counter() - t0)

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        t0 = _time.perf_counter()
        self._wide = {}
        path = self.path
        if "/query" in path:
            endpoint = "fed-query"
        elif "/lookup" in path:
            endpoint = "fed-lookup"
        else:
            endpoint = "other"
        tracer = _tracing.TRACER
        rctx = tracer.adopt_request(
            self.headers.get(_tracing.TRACE_HEADER), endpoint
        )
        if rctx is None and endpoint != "other":
            rctx = tracer.begin_request(endpoint)
        self._rctx = rctx
        if rctx is not None:
            admit = getattr(self.server, "_admit_local", None)
            enq = getattr(admit, "enq", None)
            deq = getattr(admit, "deq", None)
            if enq is not None and deq is not None and deq > enq:
                rctx.span("admission-queue", "wait", enq, deq)
        try:
            if endpoint == "fed-query":
                _FED_REQS["query"].inc()
                self._fed_query(t0)
            elif endpoint == "fed-lookup":
                _FED_REQS["lookup"].inc()
                self._fed_lookup()
            else:
                _FED_REQS["other"].inc()
                self._json(404, {"error": f"unknown path {path}"})
        except (BrokenPipeError, ConnectionResetError):
            pass
        except FederationUnavailable as exc:
            _FED_ROUTE["unavailable"].inc()
            self._wide["refusal"] = "partial-scatter"
            try:
                self._json(
                    503,
                    {"error": str(exc), "stale": True},
                    headers={"Retry-After": "1"},
                )
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (ValueError, KeyError, TypeError) as exc:
            try:
                self._json(400, {"error": repr(exc)})
            except (BrokenPipeError, ConnectionResetError):
                pass
        finally:
            dt = _time.perf_counter() - t0
            _FED_LATENCY.observe(dt)
            if rctx is not None:
                _FED_LATENCY.exemplar(dt, rctx.trace_id)
                # wide event BEFORE teardown so the trace-id provider
                # still sees the context
                _metrics.REQUESTS.record(
                    endpoint=endpoint,
                    status=self._last_status,
                    port=self.server.server_port,
                    ns=int(dt * 1e9),
                    **self._wide,
                )
                tracer.end_request(
                    rctx, status=self._last_status, **self._wide
                )
            tracer.drop_request()

    def _fed_query(self, t0: float) -> None:
        req = self._body()
        if "vectors" in req:
            vectors = [list(map(float, v)) for v in req["vectors"]]
        else:
            vectors = [list(map(float, req["vector"]))]
        k = int(req.get("k", 10))
        front = self.server.front
        rctx = self._rctx
        key = front.cache_key(
            "fed-query",
            json.dumps({"vectors": vectors, "k": k}, sort_keys=True).encode(),
        )
        if key is not None:
            tc0 = _time.perf_counter()
            cached = _result_cache.CACHE.get(key)
            disposition = "hit" if cached is not None else "miss"
            self._wide["cache"] = disposition
            self._wide["stamp"] = repr(key[1])
            if rctx is not None:
                rctx.span(
                    "result-cache",
                    "serving",
                    tc0,
                    _time.perf_counter(),
                    disposition=disposition,
                )
            if cached is not None:
                _FED_ROUTE["cache"].inc()
                _FED_FANOUT.observe(0.0)
                self._wide["fan_out"] = 0
                self._raw_json(
                    200,
                    cached,
                    {
                        "X-Pathway-Cache": "hit",
                        "X-Pathway-Stamp": _stamp_header(key[1], None),
                    },
                )
                _result_cache.CACHE.observe_hit_latency(
                    _time.perf_counter() - t0
                )
                return
        else:
            self._wide["cache"] = "miss"
        body, answered = front.query(vectors, k, rctx=rctx)
        raw = json.dumps(body).encode()
        if key is not None and answered is not None and answered == key[1]:
            _result_cache.CACHE.put(
                key,
                raw,
                len(raw),
                # stamped at the merge's min common commit, so rollback
                # invalidation drops it with the worker-level entries
                commit_time=min(part[1] for part in answered),
            )
        meta = body.get("snapshot") or {}
        self._wide["route"] = meta.get("route")
        self._wide["fan_out"] = meta.get("fan_out", 0)
        self._wide["commit_time"] = meta.get("commit_time")
        headers = {"X-Pathway-Cache": "miss"}
        stamp_value = _stamp_header(answered, meta)
        if stamp_value is not None:
            headers["X-Pathway-Stamp"] = stamp_value
        self._raw_json(200, raw, headers)

    def _fed_lookup(self) -> None:
        req = self._body()
        keys = [str(key) for key in req.get("keys", [])]
        node = req.get("node")
        body = self.server.front.lookup(keys, node, rctx=self._rctx)
        meta = body.get("snapshot") or {}
        self._wide["fan_out"] = meta.get("fan_out", 0)
        self._wide["commit_time"] = meta.get("commit_time")
        self._json(200, body)


class FederationFront:
    """Lifecycle wrapper + routing/merging logic.  One per mesh, on the
    leader (mirrors the leader-only aggregated ``/metrics``)."""

    def __init__(
        self,
        port: int | None = None,
        worker_ports: list[int] | None = None,
        replicas: list[tuple[str, int]] | None = None,
        queue_size: int | None = None,
        threads: int | None = None,
    ) -> None:
        self.port = port if port is not None else federation_port()
        self._explicit_workers = worker_ports
        self.replicas = (
            replicas if replicas is not None else replica_endpoints()
        )
        if queue_size is None:
            queue_size = int(
                os.environ.get("PATHWAY_TPU_SERVING_QUEUE", "256")
            )
        if threads is None:
            threads = int(os.environ.get("PATHWAY_TPU_SERVING_THREADS", "8"))
        self._lock = threading.Lock()
        self._rr = 0  # guarded-by: self._lock
        self._stamp_vector: tuple | None = None  # guarded-by: self._lock
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="pw-fed-scatter"
        )
        self.httpd = _FederationHTTPServer(
            ("127.0.0.1", self.port),
            _FedHandler,
            None,  # no local store: reads go through self.front
            None,
            queue_size,
            threads,
        )
        self.httpd.front = self
        self._thread: threading.Thread | None = None
        self._poller: threading.Thread | None = None
        self._stop = threading.Event()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FederationFront":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="pw-federation-http",
            daemon=True,
        )
        self._thread.start()
        self._poller = threading.Thread(
            target=self._stamp_poll_loop, name="pw-federation-stamp",
            daemon=True,
        )
        self._poller.start()
        _metrics.FLIGHT.record(
            "federation_start",
            port=self.port,
            replicas=len(self.replicas),
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd.stop_pool()
        finally:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            if self._poller is not None:
                self._poller.join(timeout=2.0)
            self._pool.shutdown(wait=False)
        _metrics.FLIGHT.record("federation_stop", port=self.port)

    # -- topology ------------------------------------------------------------

    def worker_ports(self) -> list[int]:
        """Live per request so a rescale's new width is picked up at the
        next query, not the next process."""
        if self._explicit_workers is not None:
            return list(self._explicit_workers)
        width = int(os.environ.get("PATHWAY_PROCESSES", "1"))
        return [_server.serving_port(pid) for pid in range(width)]

    def _next_replica(self) -> list[tuple[str, int]]:
        """Replica pool rotated to start at the round-robin cursor, so
        a dead first choice falls through to the others in order."""
        if not self.replicas:
            return []
        with self._lock:
            start = self._rr % len(self.replicas)
            self._rr += 1
        return self.replicas[start:] + self.replicas[:start]

    # -- stamp poller (federated cache keying) -------------------------------

    def _stamp_poll_loop(self) -> None:
        while not self._stop.wait(0.25):
            vector = self._poll_stamps()
            with self._lock:
                self._stamp_vector = vector

    def _poll_stamps(self) -> tuple | None:
        parts = []
        for port in self.worker_ports():
            try:
                status, health = _get_json(
                    f"http://127.0.0.1:{port}/serving/health", timeout=0.5
                )
            except (OSError, ValueError):
                return None
            if status != 200 or health.get("commit_time") is None:
                return None
            parts.append((port, health["commit_time"], health.get("seq", 0)))
        return tuple(parts) or None

    def cache_key(self, endpoint: str, material: bytes):
        """Key on the poller's latest full per-worker stamp vector; None
        (no caching) while any backend is unreachable or pre-commit."""
        if not _result_cache.enabled():
            return None
        with self._lock:
            vector = self._stamp_vector
        if vector is None:
            return None
        return (
            endpoint,
            vector,
            _result_cache.query_digest(endpoint, material),
        )

    # -- routing -------------------------------------------------------------

    def query(
        self, vectors: list, k: int, rctx=None
    ) -> tuple[dict, tuple | None]:
        """Answer one federated KNN request.  Returns ``(body,
        answered_stamp_vector)``; the stamp vector is None on replica
        routes (replica answers are cached in the replica process).
        ``rctx`` is the handler's request-trace context, passed
        explicitly because the scatter pool threads don't share the
        handler's thread-local slot."""
        payload = {"vectors": vectors, "k": k}
        for host, port in self._next_replica():
            sid = rctx.alloc_sid() if rctx is not None else None
            hdrs = (
                {_tracing.TRACE_HEADER: rctx.header(sid)}
                if rctx is not None
                else None
            )
            t_leg = _time.perf_counter()
            try:
                status, body, rhdrs = _post_json(
                    f"http://{host}:{port}/serving/query",
                    payload,
                    timeout=5.0,
                    headers=hdrs,
                )
            except (OSError, ValueError) as exc:
                if rctx is not None:
                    # the dead leg stays visible in the assembled trace
                    # as the reason the request fell through to scatter
                    rctx.span(
                        f"replica {host}:{port}",
                        "exchange",
                        t_leg,
                        _time.perf_counter(),
                        sid=sid,
                        port=port,
                        error=repr(exc),
                    )
                continue
            if rctx is not None:
                rctx.span(
                    f"replica {host}:{port}",
                    "exchange",
                    t_leg,
                    _time.perf_counter(),
                    sid=sid,
                    port=port,
                    status=status,
                )
                remote = _tracing.decode_spans(
                    rhdrs.get(_tracing.SPANS_HEADER)
                    if rhdrs is not None
                    else None
                )
                if remote:
                    rctx.add_remote_spans(remote, sid)
            if status == 200 and body.get("snapshot") is not None:
                _FED_ROUTE["replica"].inc()
                _FED_FANOUT.observe(1.0)
                meta = body["snapshot"]
                meta["route"] = "replica"
                meta["fan_out"] = 1
                return body, None
        return self._scatter_query(payload, k, rctx)

    def _scatter_query(
        self, payload: dict, k: int, rctx=None
    ) -> tuple[dict, tuple | None]:
        ports = self.worker_ports()
        shard_bodies = self._scatter("/serving/query", payload, ports, rctx)
        _FED_ROUTE["scatter"].inc()
        _FED_FANOUT.observe(float(len(ports)))
        answered = []
        live = []
        for port, body in zip(ports, shard_bodies):
            meta = body.get("snapshot")
            if meta is None:
                continue  # pre-commit worker: empty contribution
            answered.append((port, meta["commit_time"], meta.get("seq", 0)))
            live.append(body)
        if not live:
            n = len(payload["vectors"])
            return {"hits": [[] for _ in range(n)], "snapshot": None}, None
        n = len(payload["vectors"])
        merged_hits = []
        for qi in range(n):
            merged: list = []
            for body in live:
                merged.extend(body["hits"][qi])
            # the ReadSnapshot.search contract verbatim: stable sort on
            # descending score, ties resolve by worker then shard order
            merged.sort(key=lambda hit: -hit[1])
            merged_hits.append(merged[:k])
        metas = [body["snapshot"] for body in live]
        meta = {
            "commit_time": min(m["commit_time"] for m in metas),
            "seq": max(m.get("seq", 0) for m in metas),
            "staleness_s": max(m.get("staleness_s", 0.0) for m in metas),
            "route": "scatter",
            "fan_out": len(ports),
        }
        return {"hits": merged_hits, "snapshot": meta}, tuple(answered)

    def lookup(self, keys: list[str], node, rctx=None) -> dict:
        payload = {"keys": keys}
        if node is not None:
            payload["node"] = node
        ports = self.worker_ports()
        shard_bodies = self._scatter("/serving/lookup", payload, ports, rctx)
        _FED_FANOUT.observe(float(len(ports)))
        rows: dict = {}
        metas = []
        for body in shard_bodies:
            meta = body.get("snapshot")
            if meta is None:
                continue
            metas.append(meta)
            for key, row in body.get("rows", {}).items():
                if row is not None or key not in rows:
                    rows[key] = row
        if not metas:
            return {"rows": {}, "snapshot": None}
        return {
            "rows": rows,
            "snapshot": {
                "commit_time": min(m["commit_time"] for m in metas),
                "seq": max(m.get("seq", 0) for m in metas),
                "staleness_s": max(m.get("staleness_s", 0.0) for m in metas),
                "route": "scatter",
                "fan_out": len(ports),
            },
        }

    def _scatter(
        self, path: str, payload: dict, ports: list[int], rctx=None
    ) -> list[dict]:
        """POST to every worker concurrently; ALL must answer 200 or the
        whole request degrades (partial merges are never served).  One
        child span per leg when the request is traced; each leg's
        outbound header carries its pre-allocated span id so the
        worker's piggybacked spans parent under it."""
        legs = []
        for port in ports:
            sid = rctx.alloc_sid() if rctx is not None else None
            hdrs = (
                {_tracing.TRACE_HEADER: rctx.header(sid)}
                if rctx is not None
                else None
            )
            legs.append(
                (
                    port,
                    sid,
                    _time.perf_counter(),
                    self._pool.submit(
                        _post_json,
                        f"http://127.0.0.1:{port}{path}",
                        payload,
                        5.0,
                        hdrs,
                    ),
                )
            )
        bodies = []
        for port, sid, t_leg, future in legs:
            try:
                status, body, rhdrs = future.result(timeout=6.0)
            except Exception as exc:  # noqa: BLE001 — degrade, never partial-merge
                if rctx is not None:
                    rctx.span(
                        f"scatter :{port}",
                        "exchange",
                        t_leg,
                        _time.perf_counter(),
                        sid=sid,
                        port=port,
                        error=repr(exc),
                    )
                # recorded on the handler thread, so the FLIGHT event
                # carries the request's trace id via the provider
                _metrics.FLIGHT.record(
                    "federation_partial_scatter",
                    port=port,
                    error=repr(exc),
                )
                raise FederationUnavailable(
                    f"worker :{port} unreachable during scatter: {exc!r}"
                ) from exc
            if rctx is not None:
                rctx.span(
                    f"scatter :{port}",
                    "exchange",
                    t_leg,
                    _time.perf_counter(),
                    sid=sid,
                    port=port,
                    status=status,
                )
                remote = _tracing.decode_spans(
                    rhdrs.get(_tracing.SPANS_HEADER)
                    if rhdrs is not None
                    else None
                )
                if remote:
                    rctx.add_remote_spans(remote, sid)
            if status != 200:
                _metrics.FLIGHT.record(
                    "federation_partial_scatter",
                    port=port,
                    status=status,
                )
                raise FederationUnavailable(
                    f"worker :{port} answered {status} during scatter"
                )
            bodies.append(body)
        return bodies

    # -- observability -------------------------------------------------------

    def health(self) -> dict:
        backends = {}
        commits = []
        for port in self.worker_ports():
            try:
                status, health = _get_json(
                    f"http://127.0.0.1:{port}/serving/health", timeout=1.0
                )
            except (OSError, ValueError):
                status, health = 0, {}
            backends[str(port)] = {
                "status": status,
                "commit_time": health.get("commit_time"),
            }
            commits.append(health.get("commit_time"))
        ok = all(b["status"] == 200 for b in backends.values())
        return {
            "ok": ok,
            "commit_time": (
                min(commits) if commits and None not in commits else None
            ),
            "workers": backends,
            "replicas": [f"{h}:{p}" for h, p in self.replicas],
        }

    def stats(self) -> dict:
        uptime = max(1e-9, _time.time() - self.httpd.started_wall)
        requests = sum(c.value for c in _FED_REQS.values())
        with self._lock:
            vector = self._stamp_vector
        return {
            "uptime_s": round(uptime, 3),
            "requests": requests,
            "qps": round(requests / uptime, 2),
            "routes": {name: c.value for name, c in _FED_ROUTE.items()},
            "fan_out": {
                "mean": round(
                    _FED_FANOUT.sum / _FED_FANOUT.count, 2
                )
                if _FED_FANOUT.count
                else None,
                "count": _FED_FANOUT.count,
            },
            "latency_ms": {
                "p50": round(_FED_LATENCY.quantile(0.50) * 1000.0, 3),
                "p95": round(_FED_LATENCY.quantile(0.95) * 1000.0, 3),
                "p99": round(_FED_LATENCY.quantile(0.99) * 1000.0, 3),
                "count": _FED_LATENCY.count,
            },
            "workers": self.worker_ports(),
            "replicas": [f"{h}:{p}" for h, p in self.replicas],
            "stamp_vector": list(vector) if vector else None,
            "cache": _result_cache.CACHE.stats(),
        }


def main(argv=None) -> int:
    """``pathway federation`` entry point: run one front until killed."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        prog="pathway federation",
        description="federated read front over worker query servers "
        "and replica pools",
    )
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument(
        "--workers", default="",
        help="comma list of worker query ports (default: derive from "
        "PATHWAY_PROCESSES and the serving port scheme)",
    )
    parser.add_argument(
        "--replicas", default=os.environ.get("PATHWAY_TPU_REPLICAS", ""),
        help="replica count or host:port list (default: none)",
    )
    args = parser.parse_args(list(argv) if argv is not None else None)

    worker_ports = (
        [int(p) for p in args.workers.split(",") if p.strip()]
        if args.workers
        else None
    )
    if args.replicas:
        spec = args.replicas.strip()
        if spec.isdigit():
            replicas = [
                ("127.0.0.1", replica_port(rid)) for rid in range(int(spec))
            ]
        else:
            replicas = parse_sources(spec)
    else:
        replicas = []
    front = FederationFront(
        port=args.port, worker_ports=worker_ports, replicas=replicas
    ).start()
    print(
        json.dumps(
            {
                "event": "federation-ready",
                "port": front.port,
                "workers": front.worker_ports(),
                "replicas": [f"{h}:{p}" for h, p in front.replicas],
            }
        ),
        flush=True,
    )
    stop = threading.Event()

    def _on_signal(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        front.stop()
    return 0
