"""Commit-stamped LRU result cache for the serving read tier.

Every cache key embeds the identity of the immutable
:class:`~pathway_tpu.serving.snapshot.ReadSnapshot` the answer was
computed against::

    (endpoint, commit_time, seq, rewrite-fingerprint, query-digest)

which makes the cache *correct by construction*: snapshots are
immutable, so a key can never map to two different answers.  A new
publication changes the store's stamp, so every lookup after it misses
and recomputes — "invalidation by publication" falls out of the keying
rather than requiring an invalidation protocol.  The one seam where a
stamp CAN be reused with different content is mesh rollback (recovery
re-drives commit times), so :meth:`ResultCache.invalidate_above` is
hooked into ``SnapshotStore.truncate`` and drops every entry stamped
past the rollback point (EdgeRAG's cost-aware cache discipline: the
cache may only ever serve bytes that a fresh recompute would produce
bit-identically).

Bounded LRU by **bytes**, not entries — cached values are serialized
response bodies whose sizes vary by orders of magnitude between a
point lookup and a fat KNN answer.

Env knobs (both live — re-read per lookup/insert, so operators can flip
the cache or resize it mid-run):

- ``PATHWAY_TPU_RESULT_CACHE`` — 0 disables lookups AND inserts
- ``PATHWAY_TPU_RESULT_CACHE_BYTES`` — byte budget (default 64 MiB)
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Any, Hashable

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.serving import snapshot as _snapshot

__all__ = ["ResultCache", "CACHE", "enabled", "byte_budget", "query_digest"]

DEFAULT_BYTES = 64 << 20

_EVENTS = {
    kind: _metrics.REGISTRY.counter(
        "pathway_serving_cache_events_total",
        "result-cache events by kind (hit/miss/evict/invalidate)",
        kind=kind,
    )
    for kind in ("hit", "miss", "evict", "invalidate")
}
_HIT_LATENCY = _metrics.REGISTRY.histogram(
    "pathway_serving_cache_hit_latency_seconds",
    "request latency when the answer was served from the result cache",
    buckets=(
        0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
        0.01, 0.025, 0.05, 0.1,
    ),
)


def enabled() -> bool:
    """Live per lookup: flipping PATHWAY_TPU_RESULT_CACHE=0 takes effect
    on the next request, not the next process."""
    return os.environ.get("PATHWAY_TPU_RESULT_CACHE", "1").lower() not in (
        "0",
        "false",
        "no",
    )


def byte_budget() -> int:
    """Live per insert, so the bound can be tightened mid-run."""
    try:
        return max(0, int(os.environ.get("PATHWAY_TPU_RESULT_CACHE_BYTES", "")))
    except ValueError:
        return DEFAULT_BYTES


def query_digest(endpoint: str, material: bytes) -> str:
    """Stable digest of one query's full identity (endpoint + canonical
    request bytes).  SHA-256 so distinct queries cannot collide into one
    cache slot within any realistic keyspace."""
    h = hashlib.sha256()
    h.update(endpoint.encode())
    h.update(b"\x00")
    h.update(material)
    return h.hexdigest()


class ResultCache:
    """Byte-bounded LRU of commit-stamped serialized answers."""

    def __init__(self, max_bytes: int | None = None) -> None:
        self.max_bytes = max_bytes  # None -> live env read per insert
        self._lock = threading.Lock()
        self._entries: OrderedDict[Hashable, tuple[Any, int, int]] = (
            OrderedDict()
        )  # guarded-by: self._lock  (key -> (value, nbytes, commit_time))
        self._bytes = 0  # guarded-by: self._lock

    # -- read side -----------------------------------------------------------

    def get(self, key: Hashable) -> Any:
        """Cached value or None; counts the hit/miss and refreshes LRU
        position on hit."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                hit = False
            else:
                self._entries.move_to_end(key)
                hit = True
        if hit:
            _EVENTS["hit"].inc()
            return entry[0]
        _EVENTS["miss"].inc()
        return None

    def observe_hit_latency(self, seconds: float) -> None:
        _HIT_LATENCY.observe(seconds)

    # -- write side ----------------------------------------------------------

    def put(
        self, key: Hashable, value: Any, nbytes: int, commit_time: int
    ) -> None:
        if not enabled():
            return
        budget = self.max_bytes if self.max_bytes is not None else byte_budget()
        nbytes = max(1, int(nbytes))
        if nbytes > budget:
            return  # one oversized answer must not wipe the whole cache
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes, int(commit_time))
            self._bytes += nbytes
            while self._bytes > budget and self._entries:
                _k, (_v, n, _t) = self._entries.popitem(last=False)
                self._bytes -= n
                evicted += 1
        if evicted:
            _EVENTS["evict"].inc(evicted)
            # recorded on the inserting request's handler thread, so the
            # event carries that request's trace id when it is sampled
            _metrics.FLIGHT.record(
                "cache_evict", evicted=evicted, bytes=self._bytes
            )

    def invalidate_above(self, commit_time: int) -> int:
        """Drop every entry stamped with ``commit_time > time`` — the
        rollback seam where the mesh re-uses commit times with
        different content.  Returns the number of entries dropped."""
        dropped = 0
        with self._lock:
            for key in list(self._entries):
                if self._entries[key][2] > commit_time:
                    _value, n, _t = self._entries.pop(key)
                    self._bytes -= n
                    dropped += 1
        if dropped:
            _EVENTS["invalidate"].inc(dropped)
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # -- accounting ----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            entries = len(self._entries)
            nbytes = self._bytes
        hits = _EVENTS["hit"].value
        misses = _EVENTS["miss"].value
        total = hits + misses
        return {
            "entries": entries,
            "bytes": nbytes,
            "max_bytes": (
                self.max_bytes if self.max_bytes is not None else byte_budget()
            ),
            "hits": hits,
            "misses": misses,
            "evictions": _EVENTS["evict"].value,
            "invalidations": _EVENTS["invalidate"].value,
            "hit_rate": round(hits / total, 4) if total else None,
            "enabled": enabled(),
        }


#: process-wide cache: the query server, replica server, and federation
#: front all insert under disjoint endpoint prefixes in the key
CACHE = ResultCache()


def _collect_cache():
    with CACHE._lock:
        entries = len(CACHE._entries)
        nbytes = CACHE._bytes
    yield (
        "pathway_serving_cache_bytes",
        "gauge",
        "bytes pinned by the serving result cache",
        {},
        float(nbytes),
    )
    yield (
        "pathway_serving_cache_entries",
        "gauge",
        "entries pinned by the serving result cache",
        {},
        float(entries),
    )


_metrics.REGISTRY.register_collector(_collect_cache)

# rollback seam: SnapshotStore.truncate (driven by
# DistributedScheduler.rollback) must also invalidate every cached
# answer stamped past the rollback point — commit times are re-used
# with different content afterwards, and the cache's contract is
# bit-identical-to-recompute
_snapshot.STORE.register_truncate_hook(CACHE.invalidate_above)
