"""Snapshot read plane: serve queries off-dataflow from per-commit views.

Opt-in via ``PATHWAY_TPU_SERVING=1``: every runner (single-worker,
sharded, TCP mesh leader AND followers) then publishes an immutable
:class:`~pathway_tpu.serving.snapshot.ReadSnapshot` of groupby/join/KNN
operator state into the process-wide :data:`STORE` at each commit
boundary — after ``DevicePipeline.drain_until``, so views sit exactly on
the exactly-once seam — and ``pw.run`` starts a
:class:`~pathway_tpu.serving.server.QueryServer` on
``21000 + PATHWAY_PROCESS_ID``.

The read tier scales past the worker processes themselves:

- each worker also streams its published snapshots to read-only
  **replicas** (:mod:`pathway_tpu.serving.stream` publisher on
  ``22000 + pid``, :mod:`pathway_tpu.serving.replica` consumers on
  ``24000 + replica_id``), so query capacity grows without widening
  ingest;
- the leader can front the whole mesh with one **federation** endpoint
  (:mod:`pathway_tpu.serving.federation` on ``23000``) that scatters,
  merges, and round-robins across replicas;
- all of them answer through the commit-stamped
  :mod:`result cache <pathway_tpu.serving.result_cache>`.

Env knobs:

- ``PATHWAY_TPU_SERVING`` — enable the plane (default off)
- ``PATHWAY_TPU_SNAPSHOT_DEPTH`` — retained snapshots (default 3)
- ``PATHWAY_TPU_SERVING_PORT_BASE`` — port base (default 21000)
- ``PATHWAY_TPU_SERVING_QUEUE`` — admission queue bound (default 256)
- ``PATHWAY_TPU_SERVING_THREADS`` — worker pool size (default 8)
- ``PATHWAY_TPU_SERVING_BATCH_WINDOW_MS`` — KNN micro-batch packing
  window (default 2 ms)
- ``PATHWAY_TPU_SERVING_STREAM_PORT_BASE`` — snapshot-stream base
  (default 22000)
- ``PATHWAY_TPU_SERVING_FEDERATION`` / ``PATHWAY_TPU_FEDERATION_PORT``
  — leader federation front (default off / 23000)
- ``PATHWAY_TPU_REPLICAS`` / ``PATHWAY_TPU_REPLICA_PORT_BASE`` /
  ``PATHWAY_TPU_REPLICA_MAX_STALENESS_S`` — replica pool for the front
  (count or host:port list), replica port base (24000), staleness
  bound (5 s, live)
- ``PATHWAY_TPU_RESULT_CACHE`` / ``PATHWAY_TPU_RESULT_CACHE_BYTES`` —
  result cache toggle (on) and byte budget (64 MiB), both live
"""

from __future__ import annotations

import os
import threading
from typing import Any

from pathway_tpu.serving.snapshot import (
    STORE,
    ReadSnapshot,
    SnapshotStore,
    StaleReadError,
)

__all__ = [
    "STORE",
    "ReadSnapshot",
    "SnapshotStore",
    "StaleReadError",
    "enabled",
    "publish_on_commit",
    "start_server",
    "stop_server",
    "query_server",
    "stream_server",
    "federation_front",
    "set_stream_epoch",
    "stream_truncate",
]

_lock = threading.Lock()
_server: Any = None
_stream: Any = None
_front: Any = None


def enabled() -> bool:
    return os.environ.get("PATHWAY_TPU_SERVING", "").lower() in (
        "1",
        "true",
        "yes",
    )


def publish_on_commit(scopes: list, time: int) -> None:
    """Runner-side publication hook (call only when :func:`enabled`,
    after the device pipeline drained through ``time``).  Also fans the
    fresh snapshot out to any subscribed replicas — a pin + enqueue per
    subscriber, serialization happens on their sender threads."""
    snap = STORE.publish(scopes, time)
    stream = _stream
    if stream is not None:
        stream.publish(snap)


def start_server() -> Any:
    """Start (or return) this process's query server, the snapshot
    stream publisher for replicas, and — on the leader, when
    ``PATHWAY_TPU_SERVING_FEDERATION=1`` — the federation front.  A bind
    failure is recorded and swallowed: serving is an accessory plane and
    must never take the dataflow down."""
    global _server, _stream, _front
    with _lock:
        if _server is not None:
            return _server
        from pathway_tpu.internals.metrics import FLIGHT

        try:
            from pathway_tpu.serving.server import QueryServer

            _server = QueryServer().start()
        except OSError as exc:
            FLIGHT.record("serving_bind_failed", error=repr(exc))
            _server = None
        if _server is not None and _stream is None:
            try:
                from pathway_tpu.serving.stream import SnapshotStreamServer

                _stream = SnapshotStreamServer().start()
            except OSError as exc:
                FLIGHT.record("snapstream_bind_failed", error=repr(exc))
                _stream = None
        process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        if _server is not None and _front is None and process_id == 0:
            from pathway_tpu.serving import federation as _federation

            if _federation.enabled():
                try:
                    _front = _federation.FederationFront().start()
                except OSError as exc:
                    FLIGHT.record(
                        "federation_bind_failed", error=repr(exc)
                    )
                    _front = None
        return _server


def stop_server() -> None:
    global _server, _stream, _front
    with _lock:
        srv, _server = _server, None
        stream, _stream = _stream, None
        front, _front = _front, None
    if front is not None:
        front.stop()
    if stream is not None:
        stream.stop()
    if srv is not None:
        srv.stop()


def query_server() -> Any:
    """The live :class:`QueryServer` or None.  (Named to avoid the
    package attribute ``serving.server`` — the submodule — which Python
    binds on first import.)"""
    return _server


def stream_server() -> Any:
    """The live :class:`SnapshotStreamServer` or None."""
    return _stream


def federation_front() -> Any:
    """The live :class:`FederationFront` or None (leader only)."""
    return _front


def set_stream_epoch(epoch: int) -> None:
    """Mesh resync hook: raise the snapshot stream's epoch floor so
    frames from a pre-resync publisher are fenced at the replicas."""
    stream = _stream
    if stream is not None:
        stream.set_epoch(epoch)


def stream_truncate(to_time: int) -> None:
    """Mesh rollback hook: fan the truncation out to replicas as an
    epoch-fenced ``snap-rollback`` command (the local store's own
    truncation — and the result cache's — ride the truncate hooks)."""
    stream = _stream
    if stream is not None:
        stream.on_truncate(to_time)
