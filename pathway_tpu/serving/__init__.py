"""Snapshot read plane: serve queries off-dataflow from per-commit views.

Opt-in via ``PATHWAY_TPU_SERVING=1``: every runner (single-worker,
sharded, TCP mesh leader AND followers) then publishes an immutable
:class:`~pathway_tpu.serving.snapshot.ReadSnapshot` of groupby/join/KNN
operator state into the process-wide :data:`STORE` at each commit
boundary — after ``DevicePipeline.drain_until``, so views sit exactly on
the exactly-once seam — and ``pw.run`` starts a
:class:`~pathway_tpu.serving.server.QueryServer` on
``21000 + PATHWAY_PROCESS_ID``.

Env knobs:

- ``PATHWAY_TPU_SERVING`` — enable the plane (default off)
- ``PATHWAY_TPU_SNAPSHOT_DEPTH`` — retained snapshots (default 3)
- ``PATHWAY_TPU_SERVING_PORT_BASE`` — port base (default 21000)
- ``PATHWAY_TPU_SERVING_QUEUE`` — admission queue bound (default 256)
- ``PATHWAY_TPU_SERVING_THREADS`` — worker pool size (default 8)
- ``PATHWAY_TPU_SERVING_BATCH_WINDOW_MS`` — KNN micro-batch packing
  window (default 2 ms)
"""

from __future__ import annotations

import os
import threading
from typing import Any

from pathway_tpu.serving.snapshot import STORE, ReadSnapshot, SnapshotStore

__all__ = [
    "STORE",
    "ReadSnapshot",
    "SnapshotStore",
    "enabled",
    "publish_on_commit",
    "start_server",
    "stop_server",
    "query_server",
]

_lock = threading.Lock()
_server: Any = None


def enabled() -> bool:
    return os.environ.get("PATHWAY_TPU_SERVING", "").lower() in (
        "1",
        "true",
        "yes",
    )


def publish_on_commit(scopes: list, time: int) -> None:
    """Runner-side publication hook (call only when :func:`enabled`,
    after the device pipeline drained through ``time``)."""
    STORE.publish(scopes, time)


def start_server() -> Any:
    """Start (or return) this process's query server.  A bind failure is
    recorded and swallowed: serving is an accessory plane and must never
    take the dataflow down."""
    global _server
    with _lock:
        if _server is not None:
            return _server
        try:
            from pathway_tpu.serving.server import QueryServer

            _server = QueryServer().start()
        except OSError as exc:
            from pathway_tpu.internals.metrics import FLIGHT

            FLIGHT.record("serving_bind_failed", error=repr(exc))
            _server = None
        return _server


def stop_server() -> None:
    global _server
    with _lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def query_server() -> Any:
    """The live :class:`QueryServer` or None.  (Named to avoid the
    package attribute ``serving.server`` — the submodule — which Python
    binds on first import.)"""
    return _server
