"""Worker-side publisher of the commit-stamped ReadSnapshot stream.

Each worker process that has serving enabled also listens on
``22000 + PATHWAY_PROCESS_ID`` (``PATHWAY_TPU_SERVING_STREAM_PORT_BASE``
overrides the base) for read-only replicas
(:mod:`pathway_tpu.serving.replica`).  A replica subscribes with a
``snap-sub`` frame and from then on receives every published
:class:`~pathway_tpu.serving.snapshot.ReadSnapshot` as an epoch-stamped
``snap`` frame, plus ``snap-rollback`` commands when mesh recovery
truncates the store.  The wire format and frame kinds live in
:mod:`pathway_tpu.engine.distributed` (same length-prefix + HMAC +
pickle contract as exchange frames; see ``SNAP_STREAM_KINDS``).

Ingest isolation: the publish hook only *pins* the snapshot and hands it
to per-subscriber sender threads — serialization (``payload()`` +
pickle) happens off the commit path, so attaching replicas costs the
ingest loop an enqueue, not a pickle.  Slow subscribers get drop-oldest
semantics: a replica that cannot keep up skips intermediate snapshots
and converges on the newest (bounded staleness, never backpressure on
ingest).

Replicas piggyback their own metrics-registry snapshots upstream as
``snap-stats`` frames; the leader's ``/metrics`` exposition renders them
under ``worker="r<replica-id>"`` labels and prunes them — along with the
timeseries ring's matching label sets — the moment the replica
disconnects (the same lifecycle mesh workers get from
``prune_mesh_metrics``).
"""

from __future__ import annotations

import os
import queue
import socket
import threading

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import timeseries as _timeseries
from pathway_tpu.serving import snapshot as _snapshot

__all__ = ["SnapshotStreamServer", "BASE_PORT", "stream_port"]

BASE_PORT = 22000

_FRAMES = {
    kind: _metrics.REGISTRY.counter(
        "pathway_serving_stream_frames_total",
        "snapshot-stream frames sent by this worker, by kind",
        kind=kind,
    )
    for kind in ("snap", "snap-hello", "snap-rollback")
}
_DROPPED = _metrics.REGISTRY.counter(
    "pathway_serving_stream_dropped_total",
    "snapshots skipped for slow subscribers (drop-oldest, newest wins)",
)


def stream_port(process_id: int | None = None) -> int:
    base = int(
        os.environ.get("PATHWAY_TPU_SERVING_STREAM_PORT_BASE", BASE_PORT)
    )
    if process_id is None:
        process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    return base + process_id


class _Subscriber:
    """One replica connection: a bounded drop-oldest queue drained by a
    dedicated sender thread, so a stalled replica never blocks publish
    or any other subscriber."""

    def __init__(self, sock: socket.socket, replica_id: int, secret: bytes):
        self.sock = sock
        self.replica_id = replica_id
        self._secret = secret
        self._queue: queue.Queue = queue.Queue(maxsize=4)
        self._stop = False
        self._thread = threading.Thread(
            target=self._sender, name=f"pw-snapstream-r{replica_id}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def send(self, frame: tuple) -> None:
        from pathway_tpu.engine.distributed import send_stream_frame

        send_stream_frame(self.sock, frame, self._secret)

    def enqueue(self, item: tuple) -> None:
        while True:
            try:
                self._queue.put_nowait(item)
                return
            except queue.Full:
                try:
                    old = self._queue.get_nowait()
                except queue.Empty:
                    continue
                if old[0] == "publish":
                    old[1].release()
                    _DROPPED.inc()

    def stop(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)
        # unpin anything still queued
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item[0] == "publish":
                item[1].release()

    def _sender(self) -> None:
        while not self._stop:
            try:
                item = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                if item[0] == "publish":
                    snap, epoch = item[1], item[2]
                    try:
                        payload = snap.payload()
                    finally:
                        snap.release()
                    self.send(("snap", epoch, payload["seq"], payload))
                    _FRAMES["snap"].inc()
                elif item[0] == "trunc":
                    to_time, epoch, pid = item[1], item[2], item[3]
                    self.send(("snap-rollback", epoch, to_time, pid))
                    _FRAMES["snap-rollback"].inc()
            except (OSError, RuntimeError):
                # socket died or the snapshot was reclaimed: the reader
                # side observes the close and runs the cleanup
                return


class SnapshotStreamServer:
    """Accepts replica subscriptions and fans published snapshots out."""

    def __init__(
        self,
        store: "_snapshot.SnapshotStore" | None = None,
        port: int | None = None,
        process_id: int | None = None,
    ) -> None:
        from pathway_tpu.engine.distributed import _mesh_secret

        self.store = store if store is not None else _snapshot.STORE
        self.port = port if port is not None else stream_port(process_id)
        self.process_id = (
            process_id
            if process_id is not None
            else int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        )
        self._secret = _mesh_secret()
        self._lock = threading.Lock()
        self._subs: list[_Subscriber] = []  # guarded-by: self._lock
        self._replica_metrics: dict[int, dict] = {}  # guarded-by: self._lock
        self._epoch = 0  # guarded-by: self._lock
        self._stop = False
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "SnapshotStreamServer":
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self.port))
        listener.listen(16)
        listener.settimeout(0.5)
        self._listener = listener
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pw-snapstream-accept", daemon=True
        )
        self._accept_thread.start()
        _metrics.FLIGHT.record("snapstream_start", port=self.port)
        return self

    def stop(self) -> None:
        self._stop = True
        listener = self._listener
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        with self._lock:
            subs, self._subs = list(self._subs), []
            self._replica_metrics = {}
        for sub in subs:
            sub.stop()
        thread = self._accept_thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)
        _metrics.FLIGHT.record("snapstream_stop", port=self.port)

    # -- epoch + publication -------------------------------------------------

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def set_epoch(self, epoch: int) -> None:
        with self._lock:
            self._epoch = max(self._epoch, int(epoch))

    def publish(self, snap: "_snapshot.ReadSnapshot") -> None:
        """Hand a freshly-published snapshot to every subscriber.  Cost
        on the commit path: one pin + one enqueue per subscriber; the
        sender threads do the serialization."""
        with self._lock:
            subs = list(self._subs)
            epoch = self._epoch
        for sub in subs:
            if snap.acquire():
                sub.enqueue(("publish", snap, epoch))

    def on_truncate(self, to_time: int) -> None:
        """Fan a store truncation out as an epoch-fenced command.  Each
        truncation incident bumps the stream epoch so the replica-side
        fence admits it exactly once (a zombie publisher's re-send of an
        older incident is rejected)."""
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
            subs = list(self._subs)
        for sub in subs:
            sub.enqueue(("trunc", int(to_time), epoch, self.process_id))

    # -- replica-side observability ------------------------------------------

    def replica_metrics_snapshot(self) -> dict[int, dict]:
        with self._lock:
            return dict(self._replica_metrics)

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    # -- connection handling -------------------------------------------------

    def _accept_loop(self) -> None:
        listener = self._listener
        while not self._stop and listener is not None:
            try:
                sock, _addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn,
                args=(sock,),
                name="pw-snapstream-conn",
                daemon=True,
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        from pathway_tpu.engine.distributed import recv_stream_frame

        sub: _Subscriber | None = None
        try:
            sock.settimeout(30.0)
            frame = recv_stream_frame(sock, self._secret)
            kind, epoch, _from_seq, replica_id = frame
            if kind != "snap-sub":
                sock.close()
                return
            sub = _Subscriber(sock, int(replica_id), self._secret)
            with self._lock:
                self._subs.append(sub)
                my_epoch = self._epoch
            sub.start()
            width = int(os.environ.get("PATHWAY_PROCESSES", "1"))
            sub.send(("snap-hello", my_epoch, width, self.process_id))
            _FRAMES["snap-hello"].inc()
            _metrics.FLIGHT.record(
                "snapstream_subscribe",
                replica=int(replica_id),
                port=self.port,
            )
            # late joiner catch-up: the newest live snapshot, if any
            snap = self.store.acquire_latest()
            if snap is not None:
                sub.enqueue(("publish", snap, my_epoch))
            # reader side: replica stats piggyback + disconnect detection
            sock.settimeout(1.0)
            while not self._stop:
                try:
                    stats = recv_stream_frame(sock, self._secret)
                except socket.timeout:
                    continue
                kind2, _epoch2, rid2, payload = stats
                if kind2 == "snap-stats" and isinstance(payload, dict):
                    with self._lock:
                        self._replica_metrics[int(rid2)] = payload
        except (ConnectionError, OSError, EOFError, ValueError):
            pass
        finally:
            if sub is not None:
                self._drop_subscriber(sub)
            else:
                try:
                    sock.close()
                except OSError:
                    pass

    def _drop_subscriber(self, sub: _Subscriber) -> None:
        """Replica disconnect: deregister, then prune its ``worker=``
        label sets from the aggregated exposition and the timeseries
        ring — the replica twin of ``prune_mesh_metrics``."""
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
            had_metrics = self._replica_metrics.pop(sub.replica_id, None)
        sub.stop()
        if had_metrics is not None:
            _timeseries.STORE.prune_workers(
                dead=(f"r{sub.replica_id}",)
            )
        _metrics.FLIGHT.record(
            "snapstream_unsubscribe", replica=sub.replica_id
        )
