"""Read-only replica: scale query capacity independently of ingest width.

A replica process subscribes to every mesh worker's snapshot stream
(:mod:`pathway_tpu.serving.stream`), keeps one
:class:`~pathway_tpu.serving.snapshot.SnapshotStore` per source worker,
and serves the standard ``/serving/*`` endpoints (same
:class:`~pathway_tpu.serving.server.QueryServer`, same result cache)
over a **consistent cut at the minimum common commit** across sources.
Mesh commits are driven synchronously by the coordinator, so every
worker shares one commit clock — the min common commit is a real
consistent state of the whole dataflow, and answers from it are
bit-identical to a client-side fan-out merge of the workers' own
snapshots at that commit.

Bounded staleness, never wrong:

- Frames are epoch-fenced (:class:`~pathway_tpu.engine.distributed.
  EpochFence`): a ``snap`` frame stamped below the fence floor is a
  zombie publisher's and is dropped; ``snap-rollback`` commands are
  admitted exactly once per epoch and truncate the per-source store
  (which also invalidates the result cache above the rollback point).
- A query whose freshest consistent cut is older than
  ``PATHWAY_TPU_REPLICA_MAX_STALENESS_S`` (live, default 5 s) is
  refused with ``503`` + ``Retry-After`` — through leader failover and
  rescale the replica keeps answering 200s while its cut is within
  bound and degrades to 503s (never 5xx, never wrong rows) beyond it.
- Rescale adaptation: ``snap-hello`` frames carry the mesh width; a
  replica built on the port-scheme source set subscribes to new workers
  and drops vanished ones automatically.

Start one with ``pathway replica --port 24000`` (CLI) or
:func:`serve` in-process.  Query ports default to
``24000 + replica_id`` (``PATHWAY_TPU_REPLICA_PORT_BASE``).
"""

from __future__ import annotations

import os
import socket
import threading
import time as _time
from typing import Any, Iterable

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.serving import result_cache as _result_cache
from pathway_tpu.serving import snapshot as _snapshot
from pathway_tpu.serving import stream as _stream
from pathway_tpu.serving.snapshot import StaleReadError

__all__ = [
    "Replica",
    "ReplicaStore",
    "StaleReadError",
    "replica_port",
    "parse_sources",
    "max_staleness_s",
    "main",
]

BASE_PORT = 24000

_FRAMES = {
    kind: _metrics.REGISTRY.counter(
        "pathway_serving_replica_frames_total",
        "snapshot-stream frames processed by this replica, by kind",
        kind=kind,
    )
    for kind in ("snap", "snap-rollback", "snap-hello", "fenced", "refused")
}
_RECONNECTS = _metrics.REGISTRY.counter(
    "pathway_serving_replica_reconnects_total",
    "source-stream reconnect attempts (failover/rescale churn)",
)
_STALE_503 = _metrics.REGISTRY.counter(
    "pathway_serving_replica_stale_total",
    "queries refused with 503 because the consistent cut exceeded "
    "the staleness bound",
)

#: live replicas in this process, for the lag/source collectors
_ACTIVE: list["Replica"] = []
_ACTIVE_LOCK = threading.Lock()


def replica_port(replica_id: int = 0) -> int:
    base = int(os.environ.get("PATHWAY_TPU_REPLICA_PORT_BASE", BASE_PORT))
    return base + int(replica_id)


def max_staleness_s() -> float:
    """Live per query: tightening the bound mid-run takes effect on the
    next request."""
    try:
        return float(
            os.environ.get("PATHWAY_TPU_REPLICA_MAX_STALENESS_S", "")
        )
    except ValueError:
        return 5.0


def parse_sources(spec: str) -> list[tuple[str, int]]:
    """``"host:port,host:port"`` (bare ports imply 127.0.0.1)."""
    out: list[tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, _, port = part.rpartition(":")
            out.append((host or "127.0.0.1", int(port)))
        else:
            out.append(("127.0.0.1", int(part)))
    return out


class _ReplicaSnapshot:
    """A pinned consistent cut: one ReadSnapshot per source worker, all
    at the same commit time.  Exposes the subset of the ReadSnapshot
    surface the query server uses; ``release`` unpins every part."""

    __slots__ = ("parts", "commit_time", "seq", "fingerprint")

    def __init__(self, parts: list[tuple[int, "_snapshot.ReadSnapshot"]]):
        self.parts = sorted(parts, key=lambda p: p[0])
        self.commit_time = min(s.commit_time for _sid, s in self.parts)
        self.seq = max(s.seq for _sid, s in self.parts)
        self.fingerprint = self.parts[0][1].fingerprint

    def search(
        self, queries: list, k: int, node: int | None = None
    ) -> list[list[tuple]]:
        """Same merge contract as :meth:`ReadSnapshot.search`: stable
        sort of the concatenated per-source hit lists on descending
        score, sources in ascending worker order — bit-identical to a
        client-side per-worker fan-out merge at this commit."""
        if len(queries) == 0:
            return []
        per_source = [s.search(queries, k, node) for _sid, s in self.parts]
        out: list[list[tuple]] = []
        for qi in range(len(queries)):
            merged: list[tuple] = []
            for rows in per_source:
                merged.extend(rows[qi])
            merged.sort(key=lambda hit: -hit[1])  # stable: source order ties
            out.append(merged[:k])
        return out

    def table(self, node: int | None = None) -> dict:
        merged: dict = {}
        for _sid, s in self.parts:
            merged.update(s.table(node))
        return merged

    def staleness_s(self, now: float | None = None) -> float:
        return max(s.staleness_s(now) for _sid, s in self.parts)

    def cache_stamp(self) -> tuple:
        return (
            self.commit_time,
            tuple((sid, s.commit_time, s.seq) for sid, s in self.parts),
            self.fingerprint,
        )

    def release(self) -> None:
        for _sid, s in self.parts:
            s.release()


class ReplicaStore:
    """Composite over per-source stores, presenting the SnapshotStore
    read surface (``acquire_latest``/``stamp``/``stats``) at the min
    common commit so :class:`QueryServer` serves it unchanged."""

    def __init__(self, max_staleness: float | None = None) -> None:
        self.max_staleness = max_staleness  # None -> live env read
        self._lock = threading.Lock()
        self._stores: dict[int, _snapshot.SnapshotStore] = (
            {}
        )  # guarded-by: self._lock

    def store_for(self, source_id: int) -> _snapshot.SnapshotStore:
        with self._lock:
            store = self._stores.get(source_id)
            if store is None:
                store = self._stores[source_id] = _snapshot.SnapshotStore()
                # rollback seam: truncating any source store invalidates
                # every cached cut stamped past the rollback point
                store.register_truncate_hook(
                    _result_cache.CACHE.invalidate_above
                )
            return store

    def drop_source(self, source_id: int) -> None:
        with self._lock:
            store = self._stores.pop(source_id, None)
        if store is not None:
            store.clear()

    def source_ids(self) -> list[int]:
        with self._lock:
            return sorted(self._stores)

    def _stores_snapshot(self) -> dict[int, _snapshot.SnapshotStore]:
        with self._lock:
            return dict(self._stores)

    def _bound(self) -> float:
        return (
            self.max_staleness
            if self.max_staleness is not None
            else max_staleness_s()
        )

    def acquire_latest(self) -> _ReplicaSnapshot | None:
        """Pin the freshest consistent cut.  None before the first full
        set of source snapshots exists (the server answers 200-empty);
        :class:`StaleReadError` when the cut exceeds the staleness
        bound (the server answers 503 + Retry-After)."""
        stores = self._stores_snapshot()
        if not stores:
            return None
        heads = {}
        for sid, store in stores.items():
            head = store.latest()
            if head is None:
                return None  # a source has never published: not ready
            heads[sid] = head
        cut_time = min(h.commit_time for h in heads.values())
        parts: list[tuple[int, _snapshot.ReadSnapshot]] = []
        fingerprint = None
        for sid, store in sorted(stores.items()):
            snap = store.acquire_at(cut_time)
            if snap is None:
                for _s, pinned in parts:
                    pinned.release()
                return None  # cut raced a truncate; next publish heals
            if fingerprint is None:
                fingerprint = snap.fingerprint
            elif snap.fingerprint != fingerprint:
                # mixed optimizer plans mid-upgrade: serving a merged
                # view would mix column layouts — refuse the cut
                snap.release()
                for _s, pinned in parts:
                    pinned.release()
                _FRAMES["refused"].inc()
                return None
            parts.append((sid, snap))
        cut = _ReplicaSnapshot(parts)
        staleness = cut.staleness_s()
        bound = self._bound()
        if staleness > bound:
            cut.release()
            _STALE_503.inc()
            # cut-level detail; the request-side 503 (with the refused
            # request's trace id) is recorded by the server's _stale
            _metrics.FLIGHT.record(
                "replica_stale_cut",
                commit_time=cut.commit_time,
                staleness_s=round(staleness, 6),
                bound_s=bound,
            )
            raise StaleReadError(
                f"replica cut at commit {cut.commit_time} is "
                f"{staleness:.3f}s stale (bound {bound:g}s) — refusing "
                "to answer beyond the staleness contract"
            )
        return cut

    def stamp(self) -> tuple | None:
        stores = self._stores_snapshot()
        if not stores:
            return None
        per_source = []
        for sid, store in sorted(stores.items()):
            st = store.stamp()
            if st is None:
                return None
            per_source.append((sid, st[0], st[1]))
        commit = min(c for _sid, c, _s in per_source)
        fingerprint = None
        head = stores[per_source[0][0]].latest()
        if head is not None:
            fingerprint = head.fingerprint
        return (commit, tuple(per_source), fingerprint)

    def lag_s(self) -> float | None:
        """Age of the freshest consistent cut (the replica-lag gauge)."""
        stores = self._stores_snapshot()
        if not stores:
            return None
        oldest = None
        for store in stores.values():
            head = store.latest()
            if head is None:
                return None
            age = head.staleness_s()
            oldest = age if oldest is None else max(oldest, age)
        return oldest

    def stats(self) -> dict:
        stores = self._stores_snapshot()
        per_source = {str(sid): s.stats() for sid, s in sorted(stores.items())}
        commits = [
            st["commit_time"]
            for st in per_source.values()
            if st["commit_time"] is not None
        ]
        lag = self.lag_s()
        return {
            "replica": True,
            "sources": len(stores),
            "cut_commit_time": (
                min(commits) if len(commits) == len(per_source) and commits
                else None
            ),
            "lag_s": round(lag, 6) if lag is not None else None,
            "max_staleness_s": self._bound(),
            "per_source": per_source,
            # QueryServer /serving/health parity fields
            "depth": sum(st["depth"] for st in per_source.values()),
            "seq": max(
                (st["seq"] for st in per_source.values()), default=0
            ),
            "commit_time": (
                min(commits) if len(commits) == len(per_source) and commits
                else None
            ),
            "staleness_s": round(lag, 6) if lag is not None else None,
        }


class _SourceSub:
    """Subscriber thread for one worker's snapshot stream: dial,
    handshake, ingest frames into the per-source store, reconnect with
    backoff through failover and rescale."""

    def __init__(
        self, replica: "Replica", source_id: int, host: str, port: int
    ) -> None:
        self.replica = replica
        self.source_id = source_id
        self.host = host
        self.port = port
        self.store = replica.store.store_for(source_id)
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._fence_obj = None  # EpochFence, built lazily (heavy import)
        self._last_stats_push = 0.0
        self._thread = threading.Thread(
            target=self._run,
            name=f"pw-replica-sub-{source_id}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)

    # -- wire helpers --------------------------------------------------------

    def _send(self, frame: tuple) -> None:
        from pathway_tpu.engine.distributed import send_stream_frame

        send_stream_frame(self._sock, frame, self.replica.secret)

    def _recv(self) -> Any:
        from pathway_tpu.engine.distributed import recv_stream_frame

        return recv_stream_frame(self._sock, self.replica.secret)

    def _fence(self):
        if self._fence_obj is None:
            from pathway_tpu.engine.distributed import EpochFence

            self._fence_obj = EpochFence()
        return self._fence_obj

    # -- subscription loop ---------------------------------------------------

    def _run(self) -> None:
        backoff = 0.2
        while not self._stop.is_set():
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=2.0
                )
                sock.settimeout(1.0)
                self._sock = sock
                last = self.store.latest()
                self._send(
                    (
                        "snap-sub",
                        self._fence().floor("snap"),
                        last.seq if last is not None else 0,
                        self.replica.replica_id,
                    )
                )
                backoff = 0.2
                while not self._stop.is_set():
                    try:
                        frame = self._recv()
                    except socket.timeout:
                        self._maybe_push_stats()
                        continue
                    self._handle_frame(frame)
            except (ConnectionError, OSError, EOFError, ValueError):
                pass
            finally:
                sock = self._sock
                self._sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if self._stop.is_set():
                return
            _RECONNECTS.inc()
            # bounded backoff, polled so stop() never waits long
            self._stop.wait(backoff)
            backoff = min(backoff * 2.0, 2.0)

    def _handle_frame(self, frame: Any) -> None:
        kind, epoch, a, b = frame
        if kind == "snap":
            fence = self._fence()
            floor = fence.floor("snap")
            if epoch > floor:
                fence.admit("snap", epoch)
            elif epoch < floor:
                _FRAMES["fenced"].inc()
                return  # zombie publisher from before the fence rose
            try:
                self.store.restore(b)
            except ValueError:
                _FRAMES["refused"].inc()  # format/fingerprint mismatch
                return
            _FRAMES["snap"].inc()
            self.replica.mark_frame()
        elif kind == "snap-rollback":
            fence = self._fence()
            if not fence.admit("snap-rollback", epoch):
                return  # duplicated/zombie command: already executed
            self._handle_rollback(int(a))
        elif kind == "snap-hello":
            _FRAMES["snap-hello"].inc()
            self.replica.on_width(int(a))

    def _handle_rollback(self, to_time: int) -> None:
        # truncate fires the result-cache invalidation hook; the next
        # admitted snap frame republishes past this point
        self.store.truncate(to_time)
        _FRAMES["snap-rollback"].inc()

    def _maybe_push_stats(self) -> None:
        """Piggyback this replica's registry snapshot upstream (to the
        leader only) so the mesh ``/metrics`` exposition carries
        ``worker="r<id>"`` label sets while we are connected."""
        if self.source_id != 0:
            return
        now = _time.monotonic()
        if now - self._last_stats_push < 1.5:
            return
        self._last_stats_push = now
        snap = _metrics.full_snapshot(None)
        self._send(
            (
                "snap-stats",
                self._fence().floor("snap"),
                self.replica.replica_id,
                snap,
            )
        )


class Replica:
    """Lifecycle wrapper: per-source subscribers + a QueryServer over
    the consistent cut."""

    def __init__(
        self,
        sources: list[tuple[str, int]] | None = None,
        port: int | None = None,
        replica_id: int = 0,
        max_staleness: float | None = None,
        width: int | None = None,
        host: str = "127.0.0.1",
    ) -> None:
        from pathway_tpu.engine.distributed import _mesh_secret
        from pathway_tpu.serving.server import QueryServer

        self.replica_id = int(replica_id)
        self.secret = _mesh_secret()
        self._port_scheme = sources is None
        self._scheme_host = host
        if sources is None:
            if width is None:
                width = int(os.environ.get("PATHWAY_PROCESSES", "1"))
            sources = [
                (host, _stream.stream_port(pid)) for pid in range(width)
            ]
        self.store = ReplicaStore(max_staleness=max_staleness)
        self.port = port if port is not None else replica_port(replica_id)
        self.server = QueryServer(
            store=self.store, port=self.port, batch_window_ms=0.0
        )
        self._lock = threading.Lock()
        self._subs: dict[int, _SourceSub] = {}  # guarded-by: self._lock
        self._last_frame_wall = 0.0
        for sid, (src_host, src_port) in enumerate(sources):
            self._subs[sid] = _SourceSub(self, sid, src_host, src_port)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "Replica":
        self.server.start()
        with self._lock:
            subs = list(self._subs.values())
        for sub in subs:
            sub.start()
        with _ACTIVE_LOCK:
            if self not in _ACTIVE:
                _ACTIVE.append(self)
        _metrics.FLIGHT.record(
            "replica_start", replica=self.replica_id, port=self.port
        )
        return self

    def stop(self) -> None:
        with _ACTIVE_LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        with self._lock:
            subs = list(self._subs.values())
            self._subs = {}
        for sub in subs:
            sub.stop()
        self.server.stop()
        _metrics.FLIGHT.record("replica_stop", replica=self.replica_id)

    def mark_frame(self) -> None:
        self._last_frame_wall = _time.time()

    def on_width(self, width: int) -> None:
        """Rescale adaptation (port-scheme source sets only): subscribe
        to new workers, drop sources beyond the new width."""
        if not self._port_scheme or width < 1:
            return
        added: list[_SourceSub] = []
        dropped: list[_SourceSub] = []
        with self._lock:
            for sid in list(self._subs):
                if sid >= width:
                    dropped.append(self._subs.pop(sid))
            for sid in range(width):
                if sid not in self._subs:
                    sub = _SourceSub(
                        self,
                        sid,
                        self._scheme_host,
                        _stream.stream_port(sid),
                    )
                    self._subs[sid] = sub
                    added.append(sub)
        for sub in dropped:
            sub.stop()
            self.store.drop_source(sub.source_id)
        for sub in added:
            sub.start()
        if added or dropped:
            _metrics.FLIGHT.record(
                "replica_rescale",
                replica=self.replica_id,
                width=width,
            )

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Block until a full consistent cut exists (bench/test helper)."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            try:
                cut = self.store.acquire_latest()
            except StaleReadError:
                cut = None
            if cut is not None:
                cut.release()
                return True
            _time.sleep(0.05)
        return False


def _collect_replica():
    with _ACTIVE_LOCK:
        replicas = list(_ACTIVE)
    for rep in replicas:
        lag = rep.store.lag_s()
        labels = {"replica": str(rep.replica_id)}
        if lag is not None:
            yield (
                "pathway_serving_replica_lag_seconds",
                "gauge",
                "age of this replica's freshest consistent cut",
                labels,
                float(lag),
            )
        yield (
            "pathway_serving_replica_sources",
            "gauge",
            "worker snapshot streams this replica subscribes to",
            labels,
            float(len(rep.store.source_ids())),
        )


_metrics.REGISTRY.register_collector(_collect_replica)


def main(argv: Iterable[str] | None = None) -> int:
    """``pathway replica`` entry point: run one replica until killed."""
    import argparse
    import json as _json
    import signal

    parser = argparse.ArgumentParser(
        prog="pathway replica",
        description="read-only serving replica over the snapshot stream",
    )
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--replica-id", type=int, default=0)
    parser.add_argument(
        "--sources",
        default=os.environ.get("PATHWAY_TPU_REPLICA_SOURCES", ""),
        help="host:port list of worker stream endpoints "
        "(default: derive from --width and the stream port scheme)",
    )
    parser.add_argument("--width", type=int, default=None)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--max-staleness-s", type=float, default=None,
        help="override PATHWAY_TPU_REPLICA_MAX_STALENESS_S",
    )
    args = parser.parse_args(list(argv) if argv is not None else None)

    sources = parse_sources(args.sources) if args.sources else None
    rep = Replica(
        sources=sources,
        port=args.port,
        replica_id=args.replica_id,
        max_staleness=args.max_staleness_s,
        width=args.width,
        host=args.host,
    ).start()
    print(
        _json.dumps(
            {
                "event": "replica-ready",
                "replica_id": rep.replica_id,
                "port": rep.port,
                "sources": rep.store.source_ids(),
            }
        ),
        flush=True,
    )
    stop = threading.Event()

    def _on_signal(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        rep.stop()
    return 0
