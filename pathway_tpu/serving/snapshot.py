"""Immutable per-commit read snapshots of stateful operator state.

The write path (runner commit loops) calls :meth:`SnapshotStore.publish`
at every commit boundary — after ``DevicePipeline.drain_until``, so a
published view only ever contains fully-completed device work (the same
exactly-once seam operator persistence cuts checkpoints on).  Readers
(the serving front in :mod:`pathway_tpu.serving.server`, or any
in-process consumer) acquire a refcounted :class:`ReadSnapshot` and
query it concurrently with ingest: the dataflow never blocks on a
reader, and a reader never observes a half-applied commit.

Cheapness contract (EdgeRAG's online-indexing discipline):

- **KNN state is copy-on-write.**  ``HostKnnIndex.read_view`` shares the
  live NumPy arrays and flags the index so its next in-place scatter
  clones first; an idle index publishes for the cost of two dict
  copies.  ``DeviceKnnIndex.read_view`` must device-copy (``knn_update``
  donates its input buffers), which is an HBM->HBM copy, not a transfer.
- **Table state is a shallow dict copy** of the operator's ``current``
  map (groupby/join/external-index outputs); row tuples are immutable
  and shared.
- **Reclamation is refcounted.**  The store retains the last
  ``PATHWAY_TPU_SNAPSHOT_DEPTH`` snapshots (default 3); eviction drops
  the store's own pin, and the arrays are only released when the last
  in-flight query finishes — ingest never waits, readers never see a
  freed view.

Every snapshot is stamped with its commit time and the PR-4 graph-
optimizer fingerprint.  A snapshot payload restored into a process
whose graph was rewritten differently is refused, exactly like operator
persistence (:mod:`pathway_tpu.engine.persistence`) refuses checkpoints
across optimizer-plan changes: serving rows whose column layout shifted
would be *wrong*, and the plane's contract is stale-but-never-wrong.
"""

from __future__ import annotations

import os
import threading
import time as _time
from typing import Any, Iterable

from pathway_tpu.engine.external_index import ExternalIndexNode, HostKnnIndex
from pathway_tpu.engine.graph import GroupbyNode, JoinNode
from pathway_tpu.engine.persistence import STATE_FORMAT
from pathway_tpu.internals import metrics as _metrics

__all__ = ["ReadSnapshot", "SnapshotStore", "STORE", "StaleReadError"]


class StaleReadError(RuntimeError):
    """A read-tier store's freshest consistent view is older than the
    configured staleness bound.  The HTTP layer maps this to ``503`` +
    ``Retry-After`` — a replica that has fallen too far behind refuses
    to answer rather than silently serving unboundedly stale rows (the
    plane's contract is stale-*within-bound*-but-never-wrong)."""

#: how many published snapshots the store pins (readers can pin more)
DEFAULT_DEPTH = 3

_PUBLISHED = _metrics.REGISTRY.counter(
    "pathway_serving_snapshots_published_total",
    "read snapshots published at commit boundaries",
)
_PUBLISH_S = _metrics.REGISTRY.histogram(
    "pathway_serving_publish_seconds",
    "wall time spent publishing one read snapshot (ingest-side cost)",
    buckets=(
        0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
        0.05, 0.1, 0.25, 0.5, 1.0,
    ),
)


def _depth() -> int:
    try:
        return max(1, int(os.environ.get("PATHWAY_TPU_SNAPSHOT_DEPTH", "")))
    except ValueError:
        return DEFAULT_DEPTH


class ReadSnapshot:
    """One commit's immutable read view: per-worker, per-node state.

    ``views`` is one dict per worker scope, keyed by node position,
    each entry ``{"node": class name, "table": {key: row}, "knn": view}``
    (``knn`` only on external-index nodes).  Access goes through
    :meth:`search` / :meth:`table` / :meth:`lookup`, which merge across
    worker shards with a deterministic order.

    Lifetime is refcounted: the publishing store holds one pin; every
    concurrent reader takes another via :meth:`acquire` and must
    :meth:`release`.  The view's state is dropped only when the count
    reaches zero — never mid-query.
    """

    __slots__ = (
        "commit_time",
        "seq",
        "fingerprint",
        "published_wall",
        "views",
        "_refs",
        "_lock",
    )

    def __init__(
        self,
        commit_time: int,
        seq: int,
        fingerprint: tuple,
        views: list[dict[int, dict]],
        published_wall: float | None = None,
    ) -> None:
        self.commit_time = int(commit_time)
        self.seq = int(seq)
        self.fingerprint = tuple(fingerprint)
        self.published_wall = (
            _time.time() if published_wall is None else float(published_wall)
        )
        self.views: list[dict[int, dict]] | None = views
        # the store's retention pin
        self._refs = 1  # guarded-by: self._lock
        self._lock = threading.Lock()

    # -- lifetime ------------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self.views is None

    def acquire(self) -> bool:
        """Pin the snapshot for a read; False if already reclaimed."""
        with self._lock:
            if self._refs <= 0 or self.views is None:
                return False
            self._refs += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            if self._refs <= 0:
                # last reference gone: drop the (possibly large) state so
                # the arrays and row dicts are collectable
                self.views = None

    def __enter__(self) -> "ReadSnapshot":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # -- reads ---------------------------------------------------------------

    def _entries(self, kinds: tuple | None = None) -> Iterable[tuple[int, dict]]:
        views = self.views
        if views is None:
            raise RuntimeError("read snapshot used after reclamation")
        for worker in views:
            for pos, entry in worker.items():
                if kinds is None or entry["node"] in kinds:
                    yield pos, entry

    def knn_positions(self) -> list[int]:
        return sorted({pos for pos, e in self._entries() if "knn" in e})

    def table_positions(self) -> list[int]:
        return sorted({pos for pos, _ in self._entries()})

    def search(
        self, queries: list, k: int, node: int | None = None
    ) -> list[list[tuple]]:
        """As-of-snapshot KNN: merge per-worker shard results per query.

        Each shard's ``search`` already orders hits by the ``lax.top_k``
        contract (highest score first); the merge is a stable sort of
        the concatenated per-shard lists on descending score, so ties
        resolve by worker order then within-shard order —
        deterministic, and identical to running the same merge against
        the live indexes at the same commit."""
        if len(queries) == 0:
            return []
        positions = self.knn_positions()
        if node is None:
            if not positions:
                raise LookupError("snapshot contains no KNN index state")
            node = positions[0]
        shard_results = [
            entry["knn"].search(queries, k)
            for pos, entry in self._entries()
            if pos == node and "knn" in entry
        ]
        if not shard_results:
            raise LookupError(f"no KNN index state at node position {node}")
        out: list[list[tuple]] = []
        for qi in range(len(queries)):
            merged: list[tuple] = []
            for shard in shard_results:
                merged.extend(shard[qi])
            merged.sort(key=lambda hit: -hit[1])  # stable: shard order ties
            out.append(merged[:k])
        return out

    def table(self, node: int | None = None) -> dict:
        """Merged ``{key: row}`` view of one stateful operator across
        worker shards (shards partition the key space, so the union is
        the synchronous read)."""
        positions = self.table_positions()
        if node is None:
            if not positions:
                raise LookupError("snapshot contains no table state")
            node = positions[0]
        merged: dict = {}
        found = False
        for pos, entry in self._entries():
            if pos == node:
                found = True
                merged.update(entry["table"])
        if not found:
            raise LookupError(f"no operator state at node position {node}")
        return merged

    def lookup(self, key: Any, node: int | None = None) -> Any:
        return self.table(node).get(key)

    def staleness_s(self, now: float | None = None) -> float:
        return max(0.0, (now or _time.time()) - self.published_wall)

    def cache_stamp(self) -> tuple:
        """This snapshot's result-cache identity — the same shape
        :meth:`SnapshotStore.stamp` peeks, so the handler can detect a
        publication racing between its stamp peek and the batcher's
        dispatch (insert only when they agree)."""
        return (self.commit_time, self.seq, self.fingerprint)

    # -- handoff -------------------------------------------------------------

    def payload(self) -> dict:
        """Picklable handoff payload (worker kill / failover / rescale:
        a restarted process adopts the survivor's last view so queries
        keep answering before its first commit)."""
        views = self.views
        if views is None:
            raise RuntimeError("read snapshot used after reclamation")
        workers = []
        for worker in views:
            out: dict[int, dict] = {}
            for pos, entry in worker.items():
                item: dict = {"node": entry["node"], "table": entry["table"]}
                knn = entry.get("knn")
                if knn is not None:
                    import numpy as np

                    item["knn"] = {
                        "vectors": np.asarray(knn.state.vectors),
                        "valid": np.asarray(knn.state.valid),
                        "norms": np.asarray(knn.state.norms),
                        "key_to_slot": dict(knn.key_to_slot),
                        "free": [],
                        "capacity": knn.capacity,
                        "dim": knn.dim,
                        "metric": knn.metric,
                    }
                out[pos] = item
            workers.append(out)
        return {
            "format": STATE_FORMAT,
            "optimize": list(self.fingerprint),
            "time": self.commit_time,
            "seq": self.seq,
            "published": self.published_wall,
            "workers": workers,
        }


def _capture_scope(scope: Any) -> dict[int, dict]:
    """One worker's stateful-operator views at the current (drained)
    commit boundary."""
    out: dict[int, dict] = {}
    for pos, node in enumerate(scope.nodes):
        if isinstance(node, ExternalIndexNode):
            entry: dict = {
                "node": type(node).__name__,
                "table": dict(node.current),
            }
            read_view = getattr(node.ext_index, "read_view", None)
            if read_view is not None:
                entry["knn"] = read_view()
            out[pos] = entry
        elif isinstance(node, (GroupbyNode, JoinNode)):
            out[pos] = {
                "node": type(node).__name__,
                "table": dict(node.current),
            }
    return out


class SnapshotStore:
    """Ring of the last N published snapshots with refcounted eviction.

    One store per process (module singleton :data:`STORE`); in a TCP
    mesh every process publishes its own shard views and serves them on
    its own port — the same per-process layout as the monitoring
    endpoint."""

    def __init__(self, depth: int | None = None) -> None:
        self._lock = threading.Lock()
        self._ring: list[ReadSnapshot] = []  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        self.depth = depth
        #: called with the truncation time whenever published commits
        #: are dropped (rollback / republication): the result cache
        #: invalidates its stamps, the snapshot stream fans the command
        #: out to replicas.  Registration happens at import/startup.
        self._truncate_hooks: list = []

    def register_truncate_hook(self, fn) -> None:
        if fn not in self._truncate_hooks:
            self._truncate_hooks.append(fn)

    def _fire_truncate_hooks(self, time: int) -> None:
        # called AFTER self._lock is released: hooks take their own
        # locks (cache, stream subscriber registry) and must not nest
        # under the store's
        for fn in list(self._truncate_hooks):
            try:
                fn(int(time))
            except Exception:  # noqa: BLE001 — an observer must not break publish
                pass

    # -- write side ----------------------------------------------------------

    def publish(self, scopes: list, time: int) -> ReadSnapshot:
        """Publish the commit-``time`` read view of ``scopes`` (one per
        worker).  A publication at or below an already-published commit
        time is a rollback (mesh recovery re-drives commits) or a fresh
        run reusing the process: stale future views are truncated first,
        so readers can never observe a commit the scheduler has rolled
        back past."""
        t0 = _time.perf_counter()
        fingerprint = tuple(getattr(scopes[0], "_pw_opt_fingerprint", ()))
        views = [_capture_scope(scope) for scope in scopes]
        with self._lock:
            dropped = self._truncate_locked(int(time) - 1)
            self._seq += 1
            snap = ReadSnapshot(time, self._seq, fingerprint, views)
            self._ring.append(snap)
            depth = self.depth or _depth()
            while len(self._ring) > depth:
                self._ring.pop(0).release()
        if dropped:
            # a republication below an existing commit is a rollback in
            # disguise — cached answers stamped past it must go too
            self._fire_truncate_hooks(int(time) - 1)
        _PUBLISHED.inc()
        _PUBLISH_S.observe(_time.perf_counter() - t0)
        return snap

    def truncate(self, time: int) -> None:
        """Drop every snapshot with ``commit_time > time`` (recovery
        rolled the scheduler back to ``time``)."""
        with self._lock:
            dropped = self._truncate_locked(time)
        if dropped:
            self._fire_truncate_hooks(time)

    def _truncate_locked(self, time: int) -> int:
        keep, drop = [], []
        for snap in self._ring:
            (drop if snap.commit_time > time else keep).append(snap)
        self._ring = keep
        for snap in drop:
            snap.release()
        return len(drop)

    def clear(self) -> None:
        with self._lock:
            ring, self._ring = self._ring, []
            for snap in ring:
                snap.release()
            self._seq = 0

    # -- read side -----------------------------------------------------------

    def latest(self) -> ReadSnapshot | None:
        """Most recent snapshot WITHOUT pinning (metadata peeks only —
        query paths must use :meth:`acquire_latest`)."""
        with self._lock:
            return self._ring[-1] if self._ring else None

    def acquire_latest(self) -> ReadSnapshot | None:
        """Most recent snapshot, pinned; caller must ``release()`` (or
        use it as a context manager)."""
        with self._lock:
            for snap in reversed(self._ring):
                if snap.acquire():
                    return snap
        return None

    def stamp(self) -> tuple | None:
        """Identity of the newest live snapshot for result-cache keying:
        ``(commit_time, seq, fingerprint)``.  Two equal stamps always
        name the same immutable bytes (the rollback seam, where commit
        times are re-used, is covered by the truncate hooks)."""
        with self._lock:
            for snap in reversed(self._ring):
                if not snap.closed:
                    return (snap.commit_time, snap.seq, snap.fingerprint)
        return None

    def acquire_at(self, time: int) -> ReadSnapshot | None:
        """Newest snapshot with ``commit_time <= time``, pinned."""
        with self._lock:
            for snap in reversed(self._ring):
                if snap.commit_time <= time and snap.acquire():
                    return snap
        return None

    def snapshots(self) -> list[ReadSnapshot]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> dict:
        with self._lock:
            ring = list(self._ring)
        latest = ring[-1] if ring else None
        return {
            "depth": len(ring),
            "seq": latest.seq if latest else 0,
            "commit_time": latest.commit_time if latest else None,
            "staleness_s": (
                round(latest.staleness_s(), 6) if latest else None
            ),
            "retained_commits": [s.commit_time for s in ring],
        }

    # -- handoff -------------------------------------------------------------

    def restore(
        self, payload: dict, expected_fingerprint: Iterable | None = None
    ) -> ReadSnapshot:
        """Adopt a handed-off snapshot payload (see
        :meth:`ReadSnapshot.payload`), refusing format and optimizer-
        fingerprint mismatches with the same semantics operator
        persistence applies to checkpoints."""
        fmt = payload.get("format", 1)
        if fmt != STATE_FORMAT:
            raise ValueError(
                f"read snapshot has state format {fmt}; this build writes "
                f"format {STATE_FORMAT}: serving it would answer queries "
                "under stale keys — republish from a live commit"
            )
        got = list(payload.get("optimize", []))
        if expected_fingerprint is not None:
            want = list(expected_fingerprint)
            if want != got:
                raise ValueError(
                    "read snapshot was written under a different graph-"
                    f"optimizer plan (snapshot applied {len(got)} rewrites, "
                    f"this run applies {len(want)}): its rows have a "
                    "different column layout or fusion boundary — refuse "
                    "and keep serving the local view until the next commit"
                )
        views: list[dict[int, dict]] = []
        for worker in payload.get("workers", []):
            out: dict[int, dict] = {}
            for pos, item in worker.items():
                entry: dict = {"node": item["node"], "table": item["table"]}
                knn = item.get("knn")
                if knn is not None:
                    index = HostKnnIndex(
                        knn["dim"], knn["metric"], knn["capacity"]
                    )
                    index.restore_op_state(knn)
                    entry["knn"] = index.read_view()
                out[int(pos)] = entry
            views.append(out)
        snap = ReadSnapshot(
            payload.get("time", 0),
            payload.get("seq", 0),
            tuple(got),
            views,
            published_wall=payload.get("published"),
        )
        with self._lock:
            dropped = self._truncate_locked(snap.commit_time - 1)
            self._ring.append(snap)
            self._seq = max(self._seq, snap.seq)
            depth = self.depth or _depth()
            while len(self._ring) > depth:
                self._ring.pop(0).release()
        if dropped:
            self._fire_truncate_hooks(snap.commit_time - 1)
        return snap


#: the process-wide store the runners publish into and the server reads
STORE = SnapshotStore()


def _collect_staleness():
    snap = STORE.latest()
    if snap is None:
        return
    yield (
        "pathway_serving_snapshot_staleness_seconds",
        "gauge",
        "age of the newest published read snapshot",
        {},
        snap.staleness_s(),
    )
    yield (
        "pathway_serving_snapshot_seq",
        "gauge",
        "sequence number of the newest published read snapshot",
        {},
        float(snap.seq),
    )
    yield (
        "pathway_serving_snapshot_commit_time",
        "gauge",
        "commit time of the newest published read snapshot",
        {},
        float(snap.commit_time),
    )


_metrics.REGISTRY.register_collector(_collect_staleness)
