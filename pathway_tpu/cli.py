"""The ``pathway`` CLI (reference: python/pathway/cli.py).

``python -m pathway_tpu.cli spawn --threads N --processes M prog.py args``
launches M processes of the program with the worker-topology env vars the
runtime reads (PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/RUN_ID,
reference cli.py:93-107). Threads shard the dataflow in-process
(pw.run threads=N → ShardedGraphRunner); processes partition input at the
connector, as with the reference's per-worker partitioned reads.

``spawn-from-env`` re-reads the full command from PATHWAY_SPAWN_ARGS —
the container-deployment entry point (reference spawn_from_env).
"""

from __future__ import annotations

import argparse
import os
import secrets
import shlex
import subprocess
import sys
import uuid
from typing import Sequence


def spawn(
    program: str,
    arguments: Sequence[str],
    *,
    threads: int = 1,
    processes: int = 1,
    first_port: int = 10000,
    env: dict | None = None,
) -> int:
    env_base = dict(os.environ if env is None else env)
    run_id = str(uuid.uuid4())
    # fresh per-run key authenticating exchange-mesh frames (all processes
    # share it; engine/distributed.py rejects unauthenticated frames)
    env_base.setdefault("PATHWAY_EXCHANGE_SECRET", secrets.token_hex(32))
    print(
        f"Preparing {processes} process(es) "
        f"({processes * threads} total workers)",
        file=sys.stderr,
    )
    handles = []
    try:
        for process_id in range(processes):
            proc_env = env_base.copy()
            proc_env["PATHWAY_THREADS"] = str(threads)
            proc_env["PATHWAY_PROCESSES"] = str(processes)
            proc_env["PATHWAY_FIRST_PORT"] = str(first_port)
            proc_env["PATHWAY_PROCESS_ID"] = str(process_id)
            proc_env["PATHWAY_RUN_ID"] = run_id
            handles.append(
                subprocess.Popen([program, *arguments], env=proc_env)
            )
        for handle in handles:
            handle.wait()
    finally:
        for handle in handles:
            if handle.poll() is None:
                handle.terminate()
    for handle in handles:
        rc = handle.returncode
        if rc is None:
            return 1  # never finished: failure
        if rc != 0:
            # negative = killed by signal; report 128+signal like the shell
            return rc if rc > 0 else 128 - rc
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway")
    sub = parser.add_subparsers(dest="command", required=True)

    p_spawn = sub.add_parser(
        "spawn", help="run a pathway program over N threads × M processes"
    )
    p_spawn.add_argument("--threads", "-t", type=int, default=1)
    p_spawn.add_argument("--processes", "-n", type=int, default=1)
    p_spawn.add_argument("--first-port", type=int, default=10000)
    p_spawn.add_argument("program")
    p_spawn.add_argument("arguments", nargs=argparse.REMAINDER)

    sub.add_parser(
        "spawn-from-env",
        help="run the command from the PATHWAY_SPAWN_ARGS env variable",
    )

    args = parser.parse_args(argv)
    if args.command == "spawn":
        return spawn(
            args.program,
            args.arguments,
            threads=args.threads,
            processes=args.processes,
            first_port=args.first_port,
        )
    if args.command == "spawn-from-env":
        spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "")
        if not spawn_args:
            print("PATHWAY_SPAWN_ARGS is not set", file=sys.stderr)
            return 2
        return main(["spawn", *shlex.split(spawn_args)])
    return 2


if __name__ == "__main__":
    sys.exit(main())
