"""The ``pathway`` CLI (reference: python/pathway/cli.py).

``python -m pathway_tpu.cli spawn --threads N --processes M prog.py args``
launches M processes of the program with the worker-topology env vars the
runtime reads (PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/RUN_ID,
reference cli.py:93-107). Threads shard the dataflow in-process
(pw.run threads=N → ShardedGraphRunner); processes partition input at the
connector, as with the reference's per-worker partitioned reads.

``spawn-from-env`` re-reads the full command from PATHWAY_SPAWN_ARGS —
the container-deployment entry point (reference spawn_from_env).

``python -m pathway_tpu.cli analyze prog.py args`` runs the program in
graph-only mode (PATHWAY_TPU_ANALYZE=1): every dataflow graph the program
builds is statically analyzed instead of executed, and a combined report
is printed.  Exit codes: 0 = clean (info-level findings allowed), 1 =
warning/error findings, 2 = the program or the analyzer itself failed.
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import shlex
import subprocess
import sys
import tempfile
import uuid
from typing import Sequence


def spawn(
    program: str,
    arguments: Sequence[str],
    *,
    threads: int = 1,
    processes: int = 1,
    first_port: int = 10000,
    env: dict | None = None,
) -> int:
    env_base = dict(os.environ if env is None else env)
    run_id = str(uuid.uuid4())
    # fresh per-run key authenticating exchange-mesh frames (all processes
    # share it; engine/distributed.py rejects unauthenticated frames)
    env_base.setdefault("PATHWAY_EXCHANGE_SECRET", secrets.token_hex(32))
    print(
        f"Preparing {processes} process(es) "
        f"({processes * threads} total workers)",
        file=sys.stderr,
    )
    handles = []
    try:
        for process_id in range(processes):
            proc_env = env_base.copy()
            proc_env["PATHWAY_THREADS"] = str(threads)
            proc_env["PATHWAY_PROCESSES"] = str(processes)
            proc_env["PATHWAY_FIRST_PORT"] = str(first_port)
            proc_env["PATHWAY_PROCESS_ID"] = str(process_id)
            proc_env["PATHWAY_RUN_ID"] = run_id
            handles.append(
                subprocess.Popen([program, *arguments], env=proc_env)
            )
        for handle in handles:
            handle.wait()
    finally:
        for handle in handles:
            if handle.poll() is None:
                handle.terminate()
    for handle in handles:
        rc = handle.returncode
        if rc is None:
            return 1  # never finished: failure
        if rc != 0:
            # negative = killed by signal; report 128+signal like the shell
            return rc if rc > 0 else 128 - rc
    return 0


def analyze(
    program: str,
    arguments: Sequence[str],
    *,
    as_json: bool = False,
    errors_only: bool = False,
    env: dict | None = None,
) -> int:
    """Run ``program`` under PATHWAY_TPU_ANALYZE=1 and report findings.

    The child builds its graphs exactly as it would for a real run; the
    schedulers intercept before any data flows and append one JSON report
    per analyzed scope to a temp file, aggregated here."""
    from pathway_tpu.analysis import Report, Severity

    fd, out_path = tempfile.mkstemp(prefix="pathway-analyze-", suffix=".jsonl")
    os.close(fd)
    child_env = dict(os.environ if env is None else env)
    child_env["PATHWAY_TPU_ANALYZE"] = "1"
    child_env["PATHWAY_TPU_ANALYZE_OUT"] = out_path
    try:
        proc = subprocess.run(
            [sys.executable, program, *arguments], env=child_env
        )
        if proc.returncode != 0:
            print(
                f"analyze: {program!r} exited with code {proc.returncode} "
                "while building its graph",
                file=sys.stderr,
            )
            return 2
        merged = Report()
        scope_count = 0
        with open(out_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                merged.merge(Report.from_dict(json.loads(line)))
                scope_count += 1
        if scope_count == 0:
            print(
                f"analyze: {program!r} built no dataflow graph (nothing "
                "reached a scheduler)",
                file=sys.stderr,
            )
            return 2
        if as_json:
            print(json.dumps(merged.to_dict(), indent=2))
        else:
            print(f"analyzed {scope_count} graph(s)")
            print(merged.render())
        if merged.internal_errors:
            return 2
        if merged.error_count:
            return 1
        if not errors_only and merged.count(Severity.WARNING):
            return 1
        return 0
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway")
    sub = parser.add_subparsers(dest="command", required=True)

    p_spawn = sub.add_parser(
        "spawn", help="run a pathway program over N threads × M processes"
    )
    p_spawn.add_argument("--threads", "-t", type=int, default=1)
    p_spawn.add_argument("--processes", "-n", type=int, default=1)
    p_spawn.add_argument("--first-port", type=int, default=10000)
    p_spawn.add_argument("program")
    p_spawn.add_argument("arguments", nargs=argparse.REMAINDER)

    sub.add_parser(
        "spawn-from-env",
        help="run the command from the PATHWAY_SPAWN_ARGS env variable",
    )

    p_analyze = sub.add_parser(
        "analyze",
        help="statically analyze the graphs a program builds, "
        "without executing them",
    )
    p_analyze.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    p_analyze.add_argument(
        "--errors-only",
        action="store_true",
        help="exit 1 only on error-severity findings (ignore warnings)",
    )
    p_analyze.add_argument("program")
    p_analyze.add_argument("arguments", nargs=argparse.REMAINDER)

    args = parser.parse_args(argv)
    if args.command == "spawn":
        return spawn(
            args.program,
            args.arguments,
            threads=args.threads,
            processes=args.processes,
            first_port=args.first_port,
        )
    if args.command == "analyze":
        return analyze(
            args.program,
            args.arguments,
            as_json=args.json,
            errors_only=args.errors_only,
        )
    if args.command == "spawn-from-env":
        spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "")
        if not spawn_args:
            print("PATHWAY_SPAWN_ARGS is not set", file=sys.stderr)
            return 2
        return main(["spawn", *shlex.split(spawn_args)])
    return 2


if __name__ == "__main__":
    sys.exit(main())
