"""The ``pathway`` CLI (reference: python/pathway/cli.py).

``python -m pathway_tpu.cli spawn --threads N --processes M prog.py args``
launches M processes of the program with the worker-topology env vars the
runtime reads (PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/RUN_ID,
reference cli.py:93-107). Threads shard the dataflow in-process
(pw.run threads=N → ShardedGraphRunner); processes partition input at the
connector, as with the reference's per-worker partitioned reads.

``spawn-from-env`` re-reads the full command from PATHWAY_SPAWN_ARGS —
the container-deployment entry point (reference spawn_from_env).

``python -m pathway_tpu.cli analyze prog.py args`` runs the program in
graph-only mode (PATHWAY_TPU_ANALYZE=1): every dataflow graph the program
builds is statically analyzed instead of executed, and a combined report
is printed.  Exit codes: 0 = clean (info-level findings allowed), 1 =
warning/error findings, 2 = the program or the analyzer itself failed.

``python -m pathway_tpu.cli rescale M`` asks a live supervised mesh
(PATHWAY_TPU_RECOVER=1 spawn) to rescale to M processes: the supervisor
quiesces the mesh at a commit boundary, re-shards the operator
snapshots, and relaunches — sink output stays bit-identical.

``python -m pathway_tpu.cli stats <port|host:port|url>`` scrapes a live
monitoring endpoint (pw.run with_http_server=True; port
20000 + process_id) and pretty-prints the mesh-wide per-worker table plus
per-family totals. ``--raw`` dumps the exposition text untouched;
``--watch N`` re-scrapes every N seconds with /timeseries sparklines.

``python -m pathway_tpu.cli profile <port|dir|file>`` merges, validates
(validate_profile), and renders sampling-profiler output — a live
``/profile`` endpoint, a PATHWAY_TPU_PROFILE_DIR of per-process
exports, or one export file; ``--json`` emits speedscope JSON,
``--folded`` collapsed-stack text.
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import shlex
import subprocess
import sys
import tempfile
import uuid
from typing import Sequence


def spawn(
    program: str,
    arguments: Sequence[str],
    *,
    threads: int = 1,
    processes: int = 1,
    first_port: int = 10000,
    env: dict | None = None,
) -> int:
    env_base = dict(os.environ if env is None else env)
    if env_base.get("PATHWAY_TPU_RECOVER", "").lower() in ("1", "true", "yes"):
        # fault-tolerant runs need a control plane that can restart dead
        # workers; hand the whole launch over to the supervisor
        from pathway_tpu.engine.supervisor import MeshSupervisor

        return MeshSupervisor(
            program,
            arguments,
            threads=threads,
            processes=processes,
            first_port=first_port,
            env=env_base,
        ).run()
    run_id = str(uuid.uuid4())
    # fresh per-run key authenticating exchange-mesh frames (all processes
    # share it; engine/distributed.py rejects unauthenticated frames)
    env_base.setdefault("PATHWAY_EXCHANGE_SECRET", secrets.token_hex(32))
    print(
        f"Preparing {processes} process(es) "
        f"({processes * threads} total workers)",
        file=sys.stderr,
    )
    handles = []
    try:
        for process_id in range(processes):
            proc_env = env_base.copy()
            proc_env["PATHWAY_THREADS"] = str(threads)
            proc_env["PATHWAY_PROCESSES"] = str(processes)
            proc_env["PATHWAY_FIRST_PORT"] = str(first_port)
            proc_env["PATHWAY_PROCESS_ID"] = str(process_id)
            proc_env["PATHWAY_RUN_ID"] = run_id
            handles.append(
                subprocess.Popen([program, *arguments], env=proc_env)
            )
        for handle in handles:
            handle.wait()
    finally:
        for handle in handles:
            if handle.poll() is None:
                handle.terminate()
    for handle in handles:
        rc = handle.returncode
        if rc is None:
            return 1  # never finished: failure
        if rc != 0:
            # negative = killed by signal; report 128+signal like the shell
            return rc if rc > 0 else 128 - rc
    return 0


def analyze_source(
    targets: Sequence[str],
    *,
    as_json: bool = False,
    errors_only: bool = False,
    strict: bool = False,
) -> int:
    """Lint the runtime's own source (``analyze --source``): the PWC
    concurrency/protocol and PWD device-plane passes over files or
    directories, same exit contract as graph mode (0 clean, 1 findings,
    2 analyzer failure).

    ``--json`` emits a machine-readable document for CI diffing: one
    record per finding — ``code``, ``path``, ``line``, ``column``,
    ``severity``, ``message``, ``waived`` — with waived findings
    included (``waived: true``) but never counted toward the exit code.
    """
    from pathway_tpu.analysis import Severity
    from pathway_tpu.analysis.source import analyze_paths

    missing = [t for t in targets if not os.path.exists(t)]
    if missing or not targets:
        print(
            f"analyze: no such source target(s): {missing or '(none given)'}",
            file=sys.stderr,
        )
        return 2
    report = analyze_paths(list(targets), root=os.getcwd())
    if as_json:
        def _rec(f):
            return {
                "code": f.code,
                "path": f.node_name,
                "line": f.node_index,
                "column": f.column,
                "severity": f.severity.value,
                "message": f.message,
                "waived": f.waived,
            }

        doc = {
            "mode": "source",
            "files": report.node_count,
            "findings": [_rec(f) for f in report.sorted_findings()]
            + [_rec(f) for f in report.waived],
            "internal_errors": list(report.internal_errors),
            "summary": {
                "errors": report.count(Severity.ERROR),
                "warnings": report.count(Severity.WARNING),
                "info": report.count(Severity.INFO),
                "waived": len(report.waived),
            },
        }
        print(json.dumps(doc, indent=2))
    else:
        print(report.render())
    if report.internal_errors or report.node_count == 0:
        return 2
    if strict and report.findings:
        return 1
    if report.error_count:
        return 1
    if not errors_only and report.count(Severity.WARNING):
        return 1
    return 0


def analyze(
    program: str,
    arguments: Sequence[str],
    *,
    as_json: bool = False,
    errors_only: bool = False,
    strict: bool = False,
    env: dict | None = None,
) -> int:
    """Run ``program`` under PATHWAY_TPU_ANALYZE=1 and report findings.

    The child builds its graphs exactly as it would for a real run; the
    schedulers intercept before any data flows and append one JSON report
    per analyzed scope to a temp file, aggregated here."""
    from pathway_tpu.analysis import Report, Severity

    fd, out_path = tempfile.mkstemp(prefix="pathway-analyze-", suffix=".jsonl")
    os.close(fd)
    child_env = dict(os.environ if env is None else env)
    child_env["PATHWAY_TPU_ANALYZE"] = "1"
    child_env["PATHWAY_TPU_ANALYZE_OUT"] = out_path
    try:
        proc = subprocess.run(
            [sys.executable, program, *arguments], env=child_env
        )
        if proc.returncode != 0:
            print(
                f"analyze: {program!r} exited with code {proc.returncode} "
                "while building its graph",
                file=sys.stderr,
            )
            return 2
        merged = Report()
        scope_count = 0
        with open(out_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                merged.merge(Report.from_dict(json.loads(line)))
                scope_count += 1
        if scope_count == 0:
            print(
                f"analyze: {program!r} built no dataflow graph (nothing "
                "reached a scheduler)",
                file=sys.stderr,
            )
            return 2
        if as_json:
            print(json.dumps(merged.to_dict(), indent=2))
        else:
            print(f"analyzed {scope_count} graph(s)")
            print(merged.render())
        if merged.internal_errors:
            return 2
        if strict and merged.findings:
            return 1
        if merged.error_count:
            return 1
        if not errors_only and merged.count(Severity.WARNING):
            return 1
        return 0
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def _stats_url(target: str) -> str:
    """Accept a bare port, host:port, or full URL; default path /metrics."""
    from urllib.parse import urlparse

    if target.isdigit():
        return f"http://127.0.0.1:{target}/metrics"
    if "://" not in target:
        target = "http://" + target
    if urlparse(target).path in ("", "/"):
        target = target.rstrip("/") + "/metrics"
    return target


def _hist_quantile(buckets: list, q: float) -> float | None:
    """Quantile from cumulative (upper_bound, count) pairs, interpolating
    linearly within the bucket (the usual Prometheus histogram_quantile).

    Returns None — not a fabricated 0.0 — when the histogram carries no
    information: zero observations, or every observation in a lone +Inf
    bucket (no finite bound to anchor an estimate)."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    if not any(ub != float("inf") for ub, _ in buckets):
        return None  # only a +Inf bucket: no finite bound to report
    rank = q * total
    lo_bound, lo_count = 0.0, 0.0
    for ub, c in buckets:
        if c >= rank:
            if ub == float("inf"):
                return lo_bound
            span = c - lo_count
            if span <= 0:
                return ub
            return lo_bound + (ub - lo_bound) * (rank - lo_count) / span
        lo_bound, lo_count = ub, c
    return buckets[-1][0]


def stats(
    target: str,
    *,
    raw: bool = False,
    timeout: float = 5.0,
    watch: float | None = None,
) -> int:
    """Scrape a monitoring endpoint and pretty-print the mesh-wide table.

    On a mesh leader the exposition carries every worker's piggybacked
    snapshot under ``worker="<process_id>"`` labels, so one scrape shows
    the whole cluster; rows without a worker label (the legacy local
    series) print as ``(local)``.  ``--watch N`` re-scrapes every N
    seconds (clearing the screen) and adds history sparklines read off
    the endpoint's ``/timeseries`` ring."""
    if watch:
        import time as _time_mod

        try:
            while True:
                sys.stdout.write("\x1b[2J\x1b[H")
                rc = _stats_once(target, raw=raw, timeout=timeout)
                if rc == 0 and not raw:
                    _print_sparklines(target, timeout=timeout)
                sys.stdout.flush()
                _time_mod.sleep(watch)
        except KeyboardInterrupt:
            return 0
    return _stats_once(target, raw=raw, timeout=timeout)


#: eight-level bar for terminal sparklines (history off /timeseries)
_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values: Sequence[float], width: int = 48) -> str:
    vals = list(values)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(
        _SPARK_CHARS[min(7, int((v - lo) / span * 8))] for v in vals
    )


#: families worth a sparkline row in ``stats --watch``, most
#: operationally interesting first (missing ones are skipped)
_WATCH_FAMILIES = (
    "pathway_device_queue_depth",
    "pathway_ingest_to_sink_latency_seconds",
    "pathway_serving_latency_seconds",
    "pathway_slo_burn_ratio",
    "pathway_commits_total",
    "pathway_profile_samples_total",
)


def _print_sparklines(
    target: str, *, timeout: float = 5.0, window_s: float = 120.0
) -> None:
    """Best-effort trend rows under the ``--watch`` table: windowed
    reads off the endpoint's ``/timeseries`` ring, one sparkline per
    series (capped).  A run without the history ring just shows none."""
    import urllib.request
    from urllib.parse import urlsplit, urlunsplit

    parts = urlsplit(_stats_url(target))
    base = urlunsplit((parts[0], parts[1], "/timeseries", "", ""))
    lines = []
    try:
        for family in _WATCH_FAMILIES:
            url = f"{base}?family={family}&window={window_s:g}"
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                result = json.loads(resp.read().decode())
            for series in result.get("series", [])[:4]:
                pts = series.get("points") or []
                if len(pts) < 2:
                    continue
                labels = series.get("labels") or {}
                tag = ",".join(
                    f"{k}={v}" for k, v in sorted(labels.items())
                )
                last = pts[-1][1]
                last_s = (
                    f"{last:.0f}" if float(last).is_integer()
                    else f"{last:.4g}"
                )
                lines.append(
                    f"  {family}{{{tag}}}"
                    f"  {_sparkline([p[1] for p in pts])}  {last_s}"
                )
            if len(lines) >= 12:
                break
    except Exception:  # noqa: BLE001 — trends are advisory, never fatal
        return
    if lines:
        print()
        print(f"trends (last {window_s:g}s):")
        for line in lines:
            print(line)


def _stats_once(
    target: str, *, raw: bool = False, timeout: float = 5.0
) -> int:
    import urllib.request

    url = _stats_url(target)
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception as e:  # noqa: BLE001 — report any scrape failure
        print(f"stats: scraping {url} failed: {e}", file=sys.stderr)
        return 2
    if raw:
        sys.stdout.write(text)
        return 0
    from pathway_tpu.internals import metrics as _metrics

    try:
        families = _metrics.parse_prometheus_text(text)
    except ValueError as e:
        print(f"stats: {url} returned a malformed exposition: {e}",
              file=sys.stderr)
        return 2

    def worker_of(labels: dict) -> str:
        return labels.get("worker", "")

    # -- per-worker mesh table -----------------------------------------------
    sums: dict[str, dict[str, float]] = {}
    lat: dict[str, list] = {}
    dev_lat: dict[str, list] = {}
    # device-resident operator kernels: (worker, kernel/op) -> value
    dev_ops_hits: dict[tuple[str, str], float] = {}
    dev_ops_ns: dict[tuple[str, str], float] = {}
    dev_ops_place: dict[tuple[str, str], float] = {}
    # snapshot read plane: per-worker serving counters / histograms
    srv_reqs: dict[str, float] = {}
    srv_shed: dict[str, float] = {}
    srv_lat: dict[str, list] = {}
    srv_stale: dict[str, float] = {}
    srv_seq: dict[str, float] = {}
    srv_uptime: dict[str, float] = {}
    # read tier: result cache, replica lag, federation fan-out
    cache_events: dict[tuple[str, str], float] = {}
    replica_lag: dict[tuple[str, str], float] = {}
    fed_reqs: dict[str, float] = {}
    fed_fanout_sum: dict[str, float] = {}
    fed_fanout_count: dict[str, float] = {}
    # continuous sampling profiler: per-worker sample counts / adaptive
    # rate / per-tick cost histogram (internals/profiling.py)
    prof_samples: dict[str, float] = {}
    prof_rate: dict[str, float] = {}
    prof_cost: dict[str, list] = {}

    def add(worker: str, col: str, value: float) -> None:
        sums.setdefault(worker, {})[col] = (
            sums.setdefault(worker, {}).get(col, 0.0) + value
        )

    col_of = {
        "pathway_output_rows_total": "out_rows",
        "pathway_operator_rows": "op_rows",
        "pathway_operator_batches_total": "batches",
        "pathway_operator_time_seconds": "op_ms",
        "pathway_exchange_events_total": "exchanges",
        "pathway_connector_entries_total": "ingested",
        "pathway_device_queue_depth": "dev_q",
        "pathway_device_occupancy_ratio": "dev_occ",
    }
    for fam_name, fam in families.items():
        col = col_of.get(fam_name)
        for name, labels, value in fam["samples"]:
            w = worker_of(labels)
            if col is not None:
                add(w, col, value * (1000.0 if col == "op_ms" else 1.0))
            elif (
                fam_name == "pathway_ingest_to_sink_latency_seconds"
                and name.endswith("_bucket")
            ):
                lat.setdefault(w, []).append((float(labels["le"]), value))
            elif (
                fam_name == "pathway_device_dispatch_complete_seconds"
                and name.endswith("_bucket")
            ):
                dev_lat.setdefault(w, []).append((float(labels["le"]), value))
            elif fam_name == "pathway_device_ops_kernel_hits_total":
                key = (w, labels.get("kernel", "?"))
                dev_ops_hits[key] = dev_ops_hits.get(key, 0.0) + value
            elif fam_name == "pathway_device_ops_kernel_ns_total":
                key = (w, labels.get("kernel", "?"))
                dev_ops_ns[key] = dev_ops_ns.get(key, 0.0) + value
            elif fam_name == "pathway_device_ops_placement":
                dev_ops_place[(w, labels.get("op", "?"))] = value
            elif fam_name == "pathway_serving_requests_total":
                srv_reqs[w] = srv_reqs.get(w, 0.0) + value
            elif fam_name == "pathway_serving_shed_total":
                srv_shed[w] = srv_shed.get(w, 0.0) + value
            elif (
                fam_name == "pathway_serving_latency_seconds"
                and name.endswith("_bucket")
            ):
                le = labels["le"]
                ub = float("inf") if le in ("+Inf", "inf") else float(le)
                srv_lat.setdefault(w, []).append((ub, value))
            elif fam_name == "pathway_serving_snapshot_staleness_seconds":
                srv_stale[w] = value
            elif fam_name == "pathway_serving_snapshot_seq":
                srv_seq[w] = value
            elif fam_name == "pathway_serving_uptime_seconds":
                srv_uptime[w] = value
            elif fam_name == "pathway_serving_cache_events_total":
                key = (w, labels.get("kind", "?"))
                cache_events[key] = cache_events.get(key, 0.0) + value
            elif fam_name == "pathway_serving_replica_lag_seconds":
                replica_lag[(w, labels.get("replica", "?"))] = value
            elif fam_name == "pathway_serving_federation_requests_total":
                fed_reqs[w] = fed_reqs.get(w, 0.0) + value
            elif fam_name == "pathway_serving_federation_fanout":
                if name.endswith("_sum"):
                    fed_fanout_sum[w] = value
                elif name.endswith("_count"):
                    fed_fanout_count[w] = value
            elif fam_name == "pathway_profile_samples_total":
                prof_samples[w] = prof_samples.get(w, 0.0) + value
            elif fam_name == "pathway_profile_rate_hz":
                prof_rate[w] = value
            elif (
                fam_name == "pathway_profile_sample_seconds"
                and name.endswith("_bucket")
            ):
                le = labels["le"]
                ub = float("inf") if le in ("+Inf", "inf") else float(le)
                prof_cost.setdefault(w, []).append((ub, value))
    # p99 exemplars: the trace id piggybacked on the deepest serving
    # latency bucket (internals/metrics.py) — joins a slow request seen
    # here straight to its assembled trace in ``cli trace --request``
    srv_exemplar: dict[str, tuple[float, str]] = {}
    for fam_name in (
        "pathway_serving_latency_seconds",
        "pathway_serving_federation_latency_seconds",
    ):
        fam = families.get(fam_name) or {}
        for _name, labels, exlabels, exvalue in fam.get("exemplars", []):
            w = worker_of(labels)
            tid = exlabels.get("trace_id")
            if tid and (
                w not in srv_exemplar or exvalue >= srv_exemplar[w][0]
            ):
                srv_exemplar[w] = (float(exvalue), str(tid))
    for w, buckets in lat.items():
        buckets.sort()
        sums.setdefault(w, {})
        sums[w]["lat_n"] = buckets[-1][1] if buckets else 0.0
        for col, q in (("lat_p50_ms", 0.5), ("lat_p99_ms", 0.99)):
            qv = _hist_quantile(buckets, q)
            if qv is not None:
                sums[w][col] = qv * 1000.0
    for w, buckets in dev_lat.items():
        # device-pipeline dispatch->complete latency (async device stage)
        buckets.sort()
        sums.setdefault(w, {})
        qv = _hist_quantile(buckets, 0.99)
        if qv is not None:
            sums[w]["dev_p99_ms"] = qv * 1000.0

    print(f"scraped {url}: {len(families)} families")
    if sums:
        cols = [
            "out_rows", "ingested", "op_rows", "batches", "op_ms",
            "exchanges", "lat_p50_ms", "lat_p99_ms", "lat_n",
            "dev_q", "dev_occ", "dev_p99_ms",
        ]
        header = ["worker"] + cols
        rows = []
        for w in sorted(sums, key=lambda k: (k != "", k)):
            vals = sums[w]
            rows.append(
                [w if w else "(local)"]
                + [
                    (f"{vals[c]:.2f}" if c.endswith("_ms") or c == "dev_occ"
                     else f"{vals[c]:.0f}") if c in vals else "-"
                    for c in cols
                ]
            )
        widths = [
            max(len(header[i]), *(len(r[i]) for r in rows))
            for i in range(len(header))
        ]
        print()
        print("  ".join(h.ljust(widths[i]) for i, h in enumerate(header)))
        for r in rows:
            print("  ".join(v.rjust(widths[i]) if i else v.ljust(widths[i])
                            for i, v in enumerate(r)))

    # -- device-resident operators -------------------------------------------
    if dev_ops_hits or dev_ops_place:
        print()
        print("device ops:")
        for (w, kernel) in sorted(dev_ops_hits):
            ms = dev_ops_ns.get((w, kernel), 0.0) / 1e6
            print(
                f"  {(w or '(local)'):<10}  kernel {kernel:<16}"
                f"  hits={dev_ops_hits[(w, kernel)]:.0f}"
                f"  device_ms={ms:.2f}"
            )
        for (w, op) in sorted(dev_ops_place):
            where = (
                "device" if dev_ops_place[(w, op)] >= 1.0 else "host"
            )
            print(
                f"  {(w or '(local)'):<10}  op     {op:<16}  -> {where}"
            )

    # -- snapshot read plane -------------------------------------------------
    if srv_reqs or srv_shed or srv_stale or srv_exemplar:
        print()
        print("serving:")
        workers = sorted(
            set(srv_reqs) | set(srv_shed) | set(srv_stale) | set(srv_lat)
            | set(srv_exemplar),
            key=lambda k: (k != "", k),
        )
        for w in workers:
            reqs = srv_reqs.get(w, 0.0)
            uptime = srv_uptime.get(w, 0.0)
            qps = f"{reqs / uptime:.1f}" if uptime > 0 else "-"
            buckets = sorted(srv_lat.get(w, []))
            quants = []
            for q in (0.50, 0.95, 0.99):
                qv = _hist_quantile(buckets, q) if buckets else None
                quants.append(f"{qv * 1000.0:.2f}" if qv is not None else "-")
            stale = srv_stale.get(w)
            print(
                f"  {(w or '(local)'):<10}"
                f"  reqs={reqs:.0f}  qps={qps}"
                f"  p50_ms={quants[0]}  p95_ms={quants[1]}"
                f"  p99_ms={quants[2]}"
                f"  shed={srv_shed.get(w, 0.0):.0f}"
                f"  snapshot_seq={srv_seq.get(w, 0.0):.0f}"
                + (f"  staleness_s={stale:.3f}" if stale is not None else "")
            )
            ex = srv_exemplar.get(w)
            if ex is not None:
                print(
                    f"  {'':<10}  p99 exemplar: {ex[1]}"
                    f"  ({ex[0] * 1000.0:.2f}ms)"
                )

    # -- read tier: result cache / replicas / federation ---------------------
    if cache_events or replica_lag or fed_reqs:
        print()
        print("read tier:")
        for w in sorted(
            {w for (w, _k) in cache_events}, key=lambda k: (k != "", k)
        ):
            hits = cache_events.get((w, "hit"), 0.0)
            misses = cache_events.get((w, "miss"), 0.0)
            total = hits + misses
            rate = f"{hits / total * 100.0:.1f}%" if total else "-"
            print(
                f"  {(w or '(local)'):<10}"
                f"  cache hit_rate={rate}"
                f"  hits={hits:.0f}  misses={misses:.0f}"
                f"  evict={cache_events.get((w, 'evict'), 0.0):.0f}"
                f"  invalidate="
                f"{cache_events.get((w, 'invalidate'), 0.0):.0f}"
            )
        for (w, rid) in sorted(replica_lag):
            print(
                f"  {(w or '(local)'):<10}"
                f"  replica {rid}  lag_s={replica_lag[(w, rid)]:.3f}"
            )
        for w in sorted(fed_reqs, key=lambda k: (k != "", k)):
            count = fed_fanout_count.get(w, 0.0)
            mean = (
                f"{fed_fanout_sum.get(w, 0.0) / count:.1f}" if count else "-"
            )
            print(
                f"  {(w or '(local)'):<10}"
                f"  federation reqs={fed_reqs[w]:.0f}"
                f"  fan_out_mean={mean}"
            )

    # -- sampling profiler ---------------------------------------------------
    if prof_samples:
        print()
        print("profiler:")
        for w in sorted(prof_samples, key=lambda k: (k != "", k)):
            buckets = sorted(prof_cost.get(w, []))
            quants = []
            for q in (0.50, 0.95, 0.99):
                qv = _hist_quantile(buckets, q) if buckets else None
                quants.append(
                    f"{qv * 1e6:.0f}" if qv is not None else "-"
                )
            rate = prof_rate.get(w)
            rate_s = f"{rate:.1f}" if rate is not None else "-"
            print(
                f"  {(w or '(local)'):<10}"
                f"  samples={prof_samples[w]:.0f}  rate_hz={rate_s}"
                f"  tick_us: p50={quants[0]}"
                f"  p95={quants[1]}  p99={quants[2]}"
            )

    # -- per-family totals ---------------------------------------------------
    print()
    name_w = max((len(n) for n in families), default=6)
    print(
        f"{'family'.ljust(name_w)}  {'type'.ljust(9)}  series  total"
        "      p50      p95      p99"
    )
    for fam_name in sorted(families):
        fam = families[fam_name]
        quants = ""
        if fam["type"] == "histogram":
            series = {
                tuple(sorted(la.items()))
                for n, la, _ in fam["samples"] if n.endswith("_count")
            }
            total = sum(
                v for n, _, v in fam["samples"] if n.endswith("_count")
            )
            quants = "  ".join(
                f"{q:>7}" for q in _family_percentiles(fam["samples"])
            )
        else:
            series = {
                tuple(sorted(la.items())) for _, la, _ in fam["samples"]
            }
            total = sum(v for _, _, v in fam["samples"])
        total_s = f"{total:.0f}" if float(total).is_integer() else f"{total:.4g}"
        print(
            f"{fam_name.ljust(name_w)}  {fam['type'].ljust(9)}  "
            f"{len(series):>6}  {total_s.rjust(5)}"
            + (f"  {quants}" if quants else "")
        )
    return 0


def _family_percentiles(
    samples: list, qs: tuple = (0.5, 0.95, 0.99)
) -> list[str]:
    """p50/p95/p99 of one histogram family, aggregated across every
    series (mesh-wide: worker labels just add counts).  Cumulative
    ``_bucket`` counts sum across series per ``le`` bound, so the merged
    sequence is itself a valid cumulative histogram."""
    merged: dict[float, float] = {}
    for n, la, v in samples:
        if not n.endswith("_bucket") or "le" not in la:
            continue
        le = la["le"]
        ub = float("inf") if le in ("+Inf", "inf") else float(le)
        merged[ub] = merged.get(ub, 0.0) + v
    buckets = sorted(merged.items())
    out = []
    for q in qs:
        val = _hist_quantile(buckets, q)
        if val is None:
            out.append("-")
        elif val == 0 or 0.001 <= abs(val) < 10000:
            out.append(f"{val:.4g}")
        else:
            out.append(f"{val:.2e}")
    return out


def _request_tree(spans: list) -> list:
    """Parent/child forest over request-span ``args.sid``/``args.parent``
    links: a fan-out leg allocates its sid before the RPC and every
    remote span adopts it as a parent, so the forest IS the scatter
    tree.  Returns serializable nodes (name/cat/track/dur_ms/children),
    siblings ordered by start time."""
    nodes: list[tuple[dict, dict]] = []
    by_sid: dict[str, dict] = {}
    for s in spans:
        args = s.get("args") or {}
        node = {
            "name": s.get("name", "?"),
            "cat": s.get("cat", ""),
            "track": s.get("pid"),
            "ts": s.get("ts", 0),
            "dur_ms": round(s.get("dur", 0) / 1000.0, 3),
            "children": [],
        }
        nodes.append((node, args))
        sid = args.get("sid")
        if sid is not None:
            by_sid.setdefault(str(sid), node)
    roots = []
    for node, args in nodes:
        parent = args.get("parent")
        pnode = by_sid.get(str(parent)) if parent is not None else None
        if pnode is not None and pnode is not node:
            pnode["children"].append(node)
        else:
            roots.append(node)
    for node, _args in nodes:
        node["children"].sort(key=lambda n: n["ts"])
    roots.sort(key=lambda n: n["ts"])
    return roots


def _assemble_requests(reports: list, want_id: str | None) -> list:
    """Merge request-trace ring entries across exported files into one
    summary per trace id.  The root process's entry holds the full
    assembly (remote spans ride the response-header piggyback); any
    hop-side leftover entry contributes spans the piggyback dropped."""
    by_id: dict[str, list[dict]] = {}
    files: dict[str, list[str]] = {}
    for rep in reports:
        for t in rep.get("traces", []):
            if t.get("kind") != "request":
                continue
            tid = str(t.get("trace_id"))
            if want_id is not None and tid != want_id:
                continue
            by_id.setdefault(tid, []).append(t)
            files.setdefault(tid, []).append(rep["file"])
    out = []
    for tid, entries in sorted(by_id.items()):
        base = max(entries, key=lambda t: len(t.get("spans") or []))
        spans = list(base.get("spans") or [])
        seen = {
            (s.get("name"), s.get("ts"), s.get("pid")) for s in spans
        }
        for t in entries:
            if t is base:
                continue
            for s in t.get("spans") or []:
                key = (s.get("name"), s.get("ts"), s.get("pid"))
                if key not in seen:
                    seen.add(key)
                    spans.append(s)
        cp = base.get("critical_path") or {}
        out.append(
            {
                "trace_id": tid,
                "endpoint": base.get("endpoint"),
                "status": base.get("status"),
                "files": sorted(set(files[tid])),
                "spans": len(spans),
                "tracks": sorted(
                    {s.get("pid") for s in spans if s.get("pid") is not None}
                ),
                "wall_ms": round(cp.get("wall_s", 0.0) * 1000.0, 3),
                "critical_path": cp,
                "request": dict(base.get("request") or {}),
                "tree": _request_tree(spans),
            }
        )
    return out


def _print_request_tree(node: dict, depth: int) -> None:
    print(
        f"    {'  ' * depth}{node['name']}"
        f"  {node['dur_ms']:.2f}ms"
        f"  [{node['cat']}]"
        f"  track={node['track']}"
    )
    for child in node["children"]:
        _print_request_tree(child, depth + 1)


def trace(
    target: str | None = None,
    *,
    as_json: bool = False,
    request: str | None = None,
) -> int:
    """Validate and summarize exported Chrome trace files.

    ``target`` is one ``pathway_trace_*.json`` file or a directory of
    them (a run's ``PATHWAY_TPU_TRACE_DIR``).  Each file is checked
    against the Chrome trace-event invariants (complete X events or
    matched B/E pairs, monotonic timestamps per track) and its
    per-commit critical-path summaries are printed.  With ``request``
    (``--request [TRACE_ID]``), read-tier request traces are assembled
    across files instead — fan-out tree plus per-hop critical path —
    optionally filtered to one trace id.  Exit 2 when any file fails
    validation (or a requested trace id is missing) — the timeline
    itself is for Perfetto (https://ui.perfetto.dev) or
    chrome://tracing."""
    import glob as _glob

    from pathway_tpu.internals import tracing as _tracing

    # `cli trace --request <dir>` reads naturally: a --request value
    # that names an existing path is the target, not a trace id
    if request is not None and request and os.path.exists(request):
        if target is None:
            target = request
        request = ""
    if target is None:
        target = os.environ.get("PATHWAY_TPU_TRACE_DIR", "")
        if not target:
            print(
                "trace: no target (pass a file/dir or set "
                "PATHWAY_TPU_TRACE_DIR)",
                file=sys.stderr,
            )
            return 2
    if os.path.isdir(target):
        paths = sorted(
            _glob.glob(os.path.join(target, "pathway_trace_*.json"))
        )
        if not paths:
            print(f"no pathway_trace_*.json files in {target}",
                  file=sys.stderr)
            return 2
    else:
        paths = [target]
    rc = 0
    reports = []
    for path in paths:
        try:
            with open(path) as fh:
                obj = json.load(fh)
            events = _tracing.validate_chrome_trace(obj)
        except (OSError, ValueError) as exc:
            print(f"{path}: INVALID — {exc}", file=sys.stderr)
            rc = 2
            continue
        other = obj.get("otherData", {}) if isinstance(obj, dict) else {}
        reports.append(
            {
                "file": path,
                "events": len(events),
                "worker": other.get("worker"),
                "traces": other.get("traces", []),
            }
        )
    if request is not None:
        summaries = _assemble_requests(reports, request or None)
        if as_json:
            print(json.dumps(summaries, indent=1))
            return rc if summaries else 2
        if not summaries:
            what = f"trace id {request}" if request else "request traces"
            print(f"no {what} in {target}", file=sys.stderr)
            return 2
        for s in summaries:
            print(
                f"request {s['trace_id']}  endpoint={s['endpoint']}  "
                f"status={s['status']}  wall={s['wall_ms']:.2f}ms  "
                f"tracks={len(s['tracks'])}  spans={s['spans']}"
            )
            cp = s["critical_path"]
            print(
                f"  per-hop: queue={cp.get('queue_wait_s', 0) * 1000:.2f}ms"
                f"  exchange={cp.get('exchange_s', 0) * 1000:.2f}ms"
                f"  host={cp.get('host_compute_s', 0) * 1000:.2f}ms"
                f"  device={cp.get('device_s', 0) * 1000:.2f}ms"
            )
            chain = cp.get("chain", [])
            if chain:
                head = " -> ".join(sp["name"] for sp in chain[:8])
                if len(chain) > 8:
                    head += " -> ..."
                print(f"  critical path: {head}")
            if s["request"]:
                kv = "  ".join(
                    f"{k}={v}" for k, v in sorted(s["request"].items())
                )
                print(f"  wide event: {kv}")
            print("  fan-out tree:")
            for node in s["tree"]:
                _print_request_tree(node, 0)
        return rc
    if as_json:
        print(json.dumps(reports, indent=1))
        return rc
    for rep in reports:
        commits = [
            t
            for t in rep["traces"]
            if t.get("kind", "commit") not in ("serving", "request")
        ]
        queries = [
            t for t in rep["traces"] if t.get("kind") == "serving"
        ]
        requests_n = len(
            [t for t in rep["traces"] if t.get("kind") == "request"]
        )
        print(f"{rep['file']}: {rep['events']} events, "
              f"{len(commits)} commit trace(s), "
              f"{len(queries)} query trace(s), "
              f"{requests_n} request trace(s)")
        for t in commits:
            cp = t.get("critical_path", {})
            chain = cp.get("chain", [])
            head = " -> ".join(s["name"] for s in chain[:6])
            if len(chain) > 6:
                head += " -> ..."
            print(
                f"  {t.get('trace_id')}  commit={t.get('commit_time')}  "
                f"wall={cp.get('wall_s', 0) * 1000:.2f}ms  "
                f"host={cp.get('host_compute_s', 0) * 1000:.2f}ms  "
                f"exchange={cp.get('exchange_s', 0) * 1000:.2f}ms  "
                f"queue={cp.get('queue_wait_s', 0) * 1000:.2f}ms  "
                f"device={cp.get('device_s', 0) * 1000:.2f}ms"
            )
            if head:
                print(f"    chain: {head}")
        if queries:
            # per-endpoint rollup: sampled serving spans from the read
            # plane (knn-batch / table-lookup)
            by_name: dict[str, list[float]] = {}
            for t in queries:
                for span in t.get("spans", []):
                    by_name.setdefault(span.get("name", "?"), []).append(
                        span.get("dur", 0) / 1000.0
                    )
            for name in sorted(by_name):
                ms = sorted(by_name[name])
                print(
                    f"  query {name:<14} n={len(ms)}  "
                    f"mean={sum(ms) / len(ms):.2f}ms  "
                    f"max={ms[-1]:.2f}ms"
                )
    return rc


def _load_profile_document(target: str, timeout: float) -> dict:
    """Resolve ``cli profile``'s target into one merged document: a
    live endpoint (port / host:port / URL — fetched from ``/profile``),
    a directory of ``pathway_profile_*.json`` exports (merged, latest
    ``seq`` per worker wins), or a single export file.  Raises
    ValueError with a printable message on any failure."""
    import glob as _glob

    from pathway_tpu.internals import profiling as _profiling

    looks_remote = (
        target.isdigit()
        or "://" in target
        or (":" in target and not os.path.exists(target))
    )
    if looks_remote:
        import urllib.request
        from urllib.parse import urlsplit, urlunsplit

        parts = urlsplit(_stats_url(target))
        url = urlunsplit((parts[0], parts[1], "/profile", "", ""))
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except Exception as exc:  # noqa: BLE001 — any fetch failure
            raise ValueError(f"fetching {url} failed: {exc}") from exc
    if os.path.isdir(target):
        paths = sorted(
            _glob.glob(os.path.join(target, "pathway_profile_*.json"))
        )
        if not paths:
            raise ValueError(
                f"no pathway_profile_*.json files in {target} "
                "(PATHWAY_TPU_PROFILE_DIR of a profiled run)"
            )
        docs = []
        for path in paths:
            try:
                with open(path) as fh:
                    docs.append(json.load(fh))
            except (OSError, ValueError) as exc:
                raise ValueError(f"{path}: unreadable — {exc}") from exc
        return _profiling.merge_documents(docs)
    if os.path.exists(target):
        try:
            with open(target) as fh:
                return json.load(fh)
        except (OSError, ValueError) as exc:
            raise ValueError(f"{target}: unreadable — {exc}") from exc
    raise ValueError(f"no such profile target: {target!r}")


def profile(
    target: str,
    *,
    as_json: bool = False,
    folded: bool = False,
    out: str | None = None,
    timeout: float = 5.0,
) -> int:
    """Merge, validate, and render sampling-profiler output.

    ``target`` is a live monitoring endpoint (``/profile`` is fetched),
    a directory of per-process ``pathway_profile_*.json`` exports, or a
    single export file.  Default output is a human summary; ``--json``
    emits speedscope JSON (load at https://www.speedscope.app),
    ``--folded`` emits collapsed-stack text (flamegraph.pl).  Every
    path goes through ``validate_profile`` — exit 2 on an invalid or
    unreachable profile."""
    from pathway_tpu.internals import profiling as _profiling

    try:
        doc = _load_profile_document(target, timeout)
        _profiling.validate_profile(doc)
    except ValueError as exc:
        print(f"profile: {exc}", file=sys.stderr)
        return 2

    if folded:
        text = _profiling.folded_text(doc)
    elif as_json:
        text = json.dumps(_profiling.speedscope(doc), indent=1) + "\n"
    else:
        lines = [f"profile: {len(doc['workers'])} worker(s)"]
        for wid in sorted(doc["workers"], key=str):
            p = doc["workers"][wid]
            lines.append(
                f"  worker {wid}: pid={p.get('pid')}  "
                f"samples={p.get('sample_count', 0)}  "
                f"rate_hz={p.get('rate_hz', 0)}  "
                f"wall_s={p.get('wall_s', 0)}  "
                f"epoch={p.get('epoch', 0)}"
                + (
                    f"  dropped_stacks={p['dropped_stacks']}"
                    if p.get("dropped_stacks")
                    else ""
                )
            )
        phases = doc.get("phases") or _profiling.phase_totals(doc)
        total = sum(phases.values()) or 1.0
        lines.append("phases (sampled seconds):")
        for phase, weight in sorted(
            phases.items(), key=lambda kv: -kv[1]
        ):
            lines.append(
                f"  {phase:<10} {weight:>10.3f}s  "
                f"{100.0 * weight / total:5.1f}%"
            )
        # hottest folded stacks across the mesh, leaf shown last
        heat: dict[tuple[str, str], float] = {}
        for p in doc["workers"].values():
            for phase, stack, weight, _count in p.get("samples", ()):
                key = (phase, stack)
                heat[key] = heat.get(key, 0.0) + float(weight)
        lines.append("hot stacks:")
        for (phase, stack), weight in sorted(
            heat.items(), key=lambda kv: -kv[1]
        )[:10]:
            leaf = stack.rsplit(";", 2)[-2:]
            lines.append(
                f"  {weight:>8.3f}s  [{phase}] {';'.join(leaf)}"
            )
        text = "\n".join(lines) + "\n"

    if out:
        with open(out, "w") as fh:
            fh.write(text)
        print(f"profile: wrote {out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


def rescale(
    target_processes: int, *, supervisor_dir: str | None = None
) -> int:
    """Ask a live supervised mesh to rescale to ``target_processes``.

    Writes a ``rescale`` request file into the supervisor's control
    directory (``--supervisor-dir`` or PATHWAY_TPU_SUPERVISOR_DIR —
    launch the run with that variable preset so other terminals can
    find it).  The supervisor quiesces the mesh at its next commit
    boundary, re-shards the operator snapshots, and relaunches at the
    new size; sink output stays bit-identical."""
    sup_dir = supervisor_dir or os.environ.get("PATHWAY_TPU_SUPERVISOR_DIR")
    if not sup_dir:
        print(
            "rescale: no supervisor directory — pass --supervisor-dir "
            "or set PATHWAY_TPU_SUPERVISOR_DIR to the value the "
            "supervised run was launched with",
            file=sys.stderr,
        )
        return 2
    if not os.path.isdir(sup_dir):
        print(
            f"rescale: supervisor directory {sup_dir!r} does not exist "
            "(is the supervised run alive?)",
            file=sys.stderr,
        )
        return 2
    if target_processes < 1:
        print(
            f"rescale: target process count must be >= 1, "
            f"got {target_processes}",
            file=sys.stderr,
        )
        return 2
    from pathway_tpu.engine.supervisor import RESCALE_REQUEST

    path = os.path.join(sup_dir, RESCALE_REQUEST)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(str(target_processes))
    os.replace(tmp, path)
    print(
        f"rescale: requested {target_processes} processes "
        f"(request file {path})",
        file=sys.stderr,
    )
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway")
    sub = parser.add_subparsers(dest="command", required=True)

    p_spawn = sub.add_parser(
        "spawn", help="run a pathway program over N threads × M processes"
    )
    p_spawn.add_argument("--threads", "-t", type=int, default=1)
    p_spawn.add_argument("--processes", "-n", type=int, default=1)
    p_spawn.add_argument("--first-port", type=int, default=10000)
    p_spawn.add_argument("program")
    p_spawn.add_argument("arguments", nargs=argparse.REMAINDER)

    sub.add_parser(
        "spawn-from-env",
        help="run the command from the PATHWAY_SPAWN_ARGS env variable",
    )

    p_analyze = sub.add_parser(
        "analyze",
        help="statically analyze the graphs a program builds, "
        "without executing them",
    )
    p_analyze.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    p_analyze.add_argument(
        "--errors-only",
        action="store_true",
        help="exit 1 only on error-severity findings (ignore warnings)",
    )
    p_analyze.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 on ANY finding, info included",
    )
    p_analyze.add_argument(
        "--source",
        action="store_true",
        help="lint runtime source instead of a graph: positional "
        "arguments are .py files/directories for the PWC concurrency "
        "and protocol passes",
    )
    p_analyze.add_argument("program")
    p_analyze.add_argument("arguments", nargs=argparse.REMAINDER)

    p_rescale = sub.add_parser(
        "rescale",
        help="ask a live supervised mesh to rescale to a new process "
        "count (quiesce + re-shard + relaunch, bit-identical sinks)",
    )
    p_rescale.add_argument(
        "--supervisor-dir",
        default=None,
        help="control directory of the supervised run (defaults to "
        "PATHWAY_TPU_SUPERVISOR_DIR)",
    )
    p_rescale.add_argument("target_processes", type=int)

    p_replica = sub.add_parser(
        "replica",
        help="run a read-only serving replica subscribed to a mesh's "
        "snapshot streams (scales query capacity without widening "
        "ingest)",
    )
    p_replica.add_argument("--port", type=int, default=None)
    p_replica.add_argument("--replica-id", type=int, default=0)
    p_replica.add_argument(
        "--sources", default=None,
        help="host:port list of worker stream endpoints (default: "
        "derive from --width and the 22000+pid port scheme)",
    )
    p_replica.add_argument("--width", type=int, default=None)
    p_replica.add_argument("--host", default="127.0.0.1")
    p_replica.add_argument("--max-staleness-s", type=float, default=None)

    p_fed = sub.add_parser(
        "federation",
        help="run a federation front: one read endpoint scattering to "
        "worker query servers and round-robining replica pools",
    )
    p_fed.add_argument("--port", type=int, default=None)
    p_fed.add_argument(
        "--workers", default=None,
        help="comma list of worker query ports (default: derive from "
        "PATHWAY_PROCESSES and the 21000+pid port scheme)",
    )
    p_fed.add_argument(
        "--replicas", default=None,
        help="replica count or host:port list (default: none)",
    )

    p_stats = sub.add_parser(
        "stats",
        help="scrape a /metrics endpoint and pretty-print the "
        "mesh-wide table",
    )
    p_stats.add_argument(
        "--raw", action="store_true",
        help="dump the raw exposition text instead of the table",
    )
    p_stats.add_argument("--timeout", type=float, default=5.0)
    p_stats.add_argument(
        "--watch", type=float, default=None, metavar="SECONDS",
        help="re-scrape every N seconds (clear screen) with history "
        "sparklines from the endpoint's /timeseries ring",
    )
    p_stats.add_argument(
        "target", help="port, host:port, or full URL of the endpoint"
    )

    p_profile = sub.add_parser(
        "profile",
        help="merge + validate + render sampling-profiler output "
        "(live /profile endpoint, a PATHWAY_TPU_PROFILE_DIR, or one "
        "export file)",
    )
    p_profile.add_argument(
        "--json", action="store_true",
        help="emit speedscope JSON (https://www.speedscope.app)",
    )
    p_profile.add_argument(
        "--folded", action="store_true",
        help="emit collapsed-stack text (flamegraph.pl / speedscope)",
    )
    p_profile.add_argument(
        "-o", "--out", default=None, help="write output to a file"
    )
    p_profile.add_argument("--timeout", type=float, default=5.0)
    p_profile.add_argument(
        "target",
        help="port / host:port / URL of a live run, a directory of "
        "pathway_profile_*.json exports, or one export file",
    )

    p_trace = sub.add_parser(
        "trace",
        help="validate + summarize exported Chrome trace files "
        "(pathway_trace_*.json; load them in Perfetto for the timeline)",
    )
    p_trace.add_argument(
        "--json", action="store_true",
        help="emit the per-trace summaries as JSON",
    )
    p_trace.add_argument(
        "--request", nargs="?", const="", default=None,
        metavar="TRACE_ID",
        help="assemble read-tier request traces across the exported "
        "files (fan-out tree + per-hop critical path), optionally "
        "filtered to one trace id",
    )
    p_trace.add_argument(
        "target", nargs="?", default=None,
        help="a trace file, or a directory of pathway_trace_*.json "
        "dumps (defaults to PATHWAY_TPU_TRACE_DIR)",
    )

    args = parser.parse_args(argv)
    if args.command == "spawn":
        return spawn(
            args.program,
            args.arguments,
            threads=args.threads,
            processes=args.processes,
            first_port=args.first_port,
        )
    if args.command == "analyze":
        if args.source:
            return analyze_source(
                [args.program, *args.arguments],
                as_json=args.json,
                errors_only=args.errors_only,
                strict=args.strict,
            )
        return analyze(
            args.program,
            args.arguments,
            as_json=args.json,
            errors_only=args.errors_only,
            strict=args.strict,
        )
    if args.command == "rescale":
        return rescale(
            args.target_processes, supervisor_dir=args.supervisor_dir
        )
    if args.command == "replica":
        from pathway_tpu.serving import replica as _replica

        replica_args = []
        if args.port is not None:
            replica_args += ["--port", str(args.port)]
        replica_args += ["--replica-id", str(args.replica_id)]
        if args.sources:
            replica_args += ["--sources", args.sources]
        if args.width is not None:
            replica_args += ["--width", str(args.width)]
        replica_args += ["--host", args.host]
        if args.max_staleness_s is not None:
            replica_args += ["--max-staleness-s", str(args.max_staleness_s)]
        return _replica.main(replica_args)
    if args.command == "federation":
        from pathway_tpu.serving import federation as _federation

        fed_args = []
        if args.port is not None:
            fed_args += ["--port", str(args.port)]
        if args.workers:
            fed_args += ["--workers", args.workers]
        if args.replicas:
            fed_args += ["--replicas", args.replicas]
        return _federation.main(fed_args)
    if args.command == "stats":
        return stats(
            args.target,
            raw=args.raw,
            timeout=args.timeout,
            watch=args.watch,
        )
    if args.command == "trace":
        return trace(
            args.target, as_json=args.json, request=args.request
        )
    if args.command == "profile":
        return profile(
            args.target,
            as_json=args.json,
            folded=args.folded,
            out=args.out,
            timeout=args.timeout,
        )
    if args.command == "spawn-from-env":
        spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "")
        if not spawn_args:
            print("PATHWAY_SPAWN_ARGS is not set", file=sys.stderr)
            return 2
        return main(["spawn", *shlex.split(spawn_args)])
    return 2


if __name__ == "__main__":
    sys.exit(main())
