"""LLM xpack: embedders, chats, rerankers, splitters, parsers, stores,
RAG pipelines and REST servers (reference: python/pathway/xpacks/llm/).

The local model path (embedders / rerankers / chats) is TPU-native JAX
(models/), jit-compiled and microbatched by the engine's batch executor;
the vector store lives in TPU HBM (stdlib/indexing over ops/knn.py).
"""

from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    mocks,
    parsers,
    prompts,
    rerankers,
    splitters,
)
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseRAGQuestionAnswerer,
    RAGClient,
    answer_with_geometric_rag_strategy,
)
from pathway_tpu.xpacks.llm.vector_store import (
    VectorStoreClient,
    VectorStoreServer,
)

__all__ = [
    "AdaptiveRAGQuestionAnswerer",
    "BaseRAGQuestionAnswerer",
    "DocumentStore",
    "RAGClient",
    "VectorStoreClient",
    "VectorStoreServer",
    "answer_with_geometric_rag_strategy",
    "embedders",
    "llms",
    "mocks",
    "parsers",
    "prompts",
    "rerankers",
    "splitters",
]
