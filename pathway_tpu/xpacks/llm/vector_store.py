"""VectorStoreServer (reference: xpacks/llm/vector_store.py:39).

A DocumentStore specialisation with a mandatory embedder and REST serving:
docs -> parse -> split -> embed (jit microbatch) -> HBM KNN; endpoints
/v1/retrieve, /v1/statistics, /v1/inputs (reference REST surface).
"""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm.document_store import DocumentStore


class VectorStoreServer(DocumentStore):
    def __init__(
        self,
        *docs: Table,
        embedder: Any,
        parser: Any = None,
        splitter: Any = None,
        index_capacity: int = 1024,
        dimensions: int | None = None,
        metric: str = "cos",
    ) -> None:
        super().__init__(
            list(docs),
            embedder=embedder,
            parser=parser,
            splitter=splitter,
            retriever_factory="knn",
            dimensions=dimensions,
            index_capacity=index_capacity,
            metric=metric,
        )

    def run_server(
        self,
        host: str = "127.0.0.1",
        port: int = 8754,
        *,
        threaded: bool = False,
        with_cache: bool = False,
    ) -> Any:
        """Serve /v1/retrieve,/v1/statistics,/v1/inputs over REST."""
        from pathway_tpu.xpacks.llm.servers import DocumentStoreServer

        server = DocumentStoreServer(host, port, self)
        return server.run(threaded=threaded, with_cache=with_cache)


class VectorStoreClient:
    """HTTP client for a VectorStoreServer (reference vector_store.py:651)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8754) -> None:
        self.base = f"http://{host}:{port}"

    def query(self, query: str, k: int = 3) -> list[dict]:
        import json
        import urllib.request

        payload = json.dumps({"query": query, "k": k}).encode()
        req = urllib.request.Request(
            self.base + "/v1/retrieve",
            data=payload,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        import json
        import urllib.request

        req = urllib.request.Request(
            self.base + "/v1/statistics",
            data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())
