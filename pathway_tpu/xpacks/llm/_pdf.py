"""Minimal native PDF text extraction.

The reference's PypdfParser delegates to the pypdf library
(reference: xpacks/llm/parsers.py:746). That library isn't in this image,
so this is a native extractor for the common machine-generated PDF shape:
FlateDecode (zlib) content streams with literal-string text operators
(``(…) Tj``, ``[(…) …] TJ``, ``'``) inside BT/ET blocks. Scanned or
exotically-encoded PDFs need OCR/vision parsing instead.
"""

from __future__ import annotations

import re
import zlib

_STREAM_RE = re.compile(
    rb"<<(?P<dict>.*?)>>\s*stream\r?\n(?P<data>.*?)endstream", re.DOTALL
)
_TEXT_BLOCK_RE = re.compile(rb"BT(.*?)ET", re.DOTALL)
# literal string followed by a show operator; also TJ arrays and ' / "
_SHOW_RE = re.compile(
    rb"""
    \((?P<lit>(?:\\.|[^\\()])*)\)\s*(?:Tj|'|") |
    \[(?P<arr>(?:\\.|[^\]])*)\]\s*TJ |
    (?P<newline>T\*|Td|TD)
    """,
    re.VERBOSE | re.DOTALL,
)
_ARR_LIT_RE = re.compile(rb"\((?P<lit>(?:\\.|[^\\()])*)\)")

_ESCAPES = {
    b"n": b"\n",
    b"r": b"\r",
    b"t": b"\t",
    b"b": b"\b",
    b"f": b"\f",
    b"(": b"(",
    b")": b")",
    b"\\": b"\\",
}


def _decode_literal(raw: bytes) -> str:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i : i + 1]
        if c == b"\\" and i + 1 < len(raw):
            nxt = raw[i + 1 : i + 2]
            if nxt in _ESCAPES:
                out += _ESCAPES[nxt]
                i += 2
                continue
            if nxt.isdigit():  # octal escape, up to 3 digits
                digits = raw[i + 1 : i + 4]
                m = re.match(rb"[0-7]{1,3}", digits)
                if m:
                    out.append(int(m.group(), 8) & 0xFF)
                    i += 1 + len(m.group())
                    continue
            i += 2
            out += nxt
            continue
        out += c
        i += 1
    return out.decode("latin-1")


def _stream_text(content: bytes) -> str:
    pieces: list[str] = []
    for block in _TEXT_BLOCK_RE.findall(content):
        line: list[str] = []
        for m in _SHOW_RE.finditer(block):
            if m.group("newline") is not None:
                if line:
                    pieces.append("".join(line))
                    line = []
                continue
            if m.group("lit") is not None:
                line.append(_decode_literal(m.group("lit")))
            elif m.group("arr") is not None:
                for lit in _ARR_LIT_RE.finditer(m.group("arr")):
                    line.append(_decode_literal(lit.group("lit")))
        if line:
            pieces.append("".join(line))
    return "\n".join(p for p in pieces if p.strip())


def extract_pdf_text(data: bytes) -> str:
    """Text of all content streams, in document order."""
    if not data.lstrip().startswith(b"%PDF"):
        raise ValueError("not a PDF (missing %PDF header)")
    texts: list[str] = []
    for m in _STREAM_RE.finditer(data):
        raw = m.group("data")
        if b"FlateDecode" in m.group("dict"):
            length = re.search(rb"/Length\s+(\d+)", m.group("dict"))
            candidates = []
            if length is not None:
                # the dict's /Length bounds the exact payload — immune to
                # compressed bytes that happen to end in EOL characters
                candidates.append(raw[: int(length.group(1))])
            candidates.append(raw)
            # at most one trailing EOL belongs to the stream framing
            candidates.append(re.sub(rb"\r?\n\Z", b"", raw))
            for candidate in candidates:
                try:
                    raw = zlib.decompress(candidate)
                    break
                except zlib.error:
                    continue
            else:
                continue
        text = _stream_text(raw)
        if text:
            texts.append(text)
    return "\n".join(texts)
