"""DocumentStore: docs -> parse -> split -> index, with retrieval queries.

Reference: xpacks/llm/document_store.py:32 (DocumentStore over a pluggable
DocumentIndexFactory; retrieve/inputs/statistics query methods). The
pipeline runs as engine dataflow: parser/splitter/embedder are UDF nodes,
the index is the as-of-now external-index operator in TPU HBM (or host BM25).
"""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import apply as pw_apply
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing import DataIndex, TantivyBM25Factory, TpuKnnFactory
from pathway_tpu.xpacks.llm.parsers import ParseUtf8
from pathway_tpu.xpacks.llm.splitters import NullSplitter


class DocumentStore:
    """Indexes documents and serves retrieval queries as dataflow.

    ``docs`` tables need a ``data`` column (bytes/str) and may carry a
    ``_metadata`` dict column. Retrieval with ``retriever_factory='knn'``
    requires ``embedder`` (any text->vector UDF).
    """

    def __init__(
        self,
        docs: Table | Sequence[Table],
        *,
        embedder: Any = None,
        parser: Any = None,
        splitter: Any = None,
        retriever_factory: str | Any = "knn",
        dimensions: int | None = None,
        index_capacity: int = 1024,
        metric: str = "cos",
    ) -> None:
        if isinstance(docs, Table):
            docs = [docs]
        self.metric = metric
        self.parser = parser or ParseUtf8()
        self.splitter = splitter or NullSplitter()
        self.embedder = embedder

        tables = []
        for d in docs:
            cols = d.column_names()
            meta = d["_metadata"] if "_metadata" in cols else None
            t = d.select(
                data=d["data"],
                _metadata=meta if meta is not None else pw_apply(lambda _x: {}, d["data"]),
            )
            tables.append(t)
        raw = tables[0].concat_reindex(*tables[1:]) if len(tables) > 1 else tables[0]
        self.input_docs = raw

        def _plain(m: Any) -> dict:
            if hasattr(m, "value"):  # Json wrapper
                m = m.value
            return dict(m or {})

        parsed = raw.select(_parts=self.parser(raw["data"]), _metadata=raw["_metadata"])
        parsed = parsed.flatten(parsed["_parts"])
        parsed = parsed.select(
            text=parsed["_parts"].get(0),
            _metadata=pw_apply(
                lambda part, meta: {**_plain(meta), **_plain(part[1])},
                parsed["_parts"],
                parsed["_metadata"],
            ),
        )
        chunked = parsed.select(
            _chunks=self.splitter(parsed["text"]), _metadata=parsed["_metadata"]
        )
        chunked = chunked.flatten(chunked["_chunks"])
        self.chunks = chunked.select(
            text=chunked["_chunks"].get(0), _metadata=chunked["_metadata"]
        )

        self._hybrid: Any = None
        if retriever_factory in ("knn", "hybrid"):
            if self.embedder is None:
                raise ValueError("knn retrieval needs an embedder")
            if dimensions is None:
                get_dim = getattr(self.embedder, "get_embedding_dimension", None)
                if get_dim is None:
                    raise ValueError("pass dimensions= for this embedder")
                dimensions = get_dim()
            data = self.chunks.select(
                text=self.chunks.text,
                _metadata=self.chunks["_metadata"],
                emb=self.embedder(self.chunks.text),
            )
            factory = TpuKnnFactory(
                dimensions=dimensions, metric=metric, capacity=index_capacity
            )
            self.indexed = data
            self.index = DataIndex(data, factory, data.emb)
            self._query_is_vector = True
            if retriever_factory == "hybrid":
                # RRF of dense KNN + BM25 over the same chunks
                # (reference hybrid_index.py:14 + vector_document_index.py)
                from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex

                bm25 = DataIndex(data, TantivyBM25Factory(), data.text)
                self._hybrid = HybridIndex([self.index, bm25])
        elif retriever_factory == "bm25":
            self.indexed = self.chunks
            self.index = DataIndex(
                self.chunks, TantivyBM25Factory(), self.chunks.text
            )
            self._query_is_vector = False
        else:
            # custom InnerIndexFactory over the text column
            self.indexed = self.chunks
            self.index = DataIndex(
                self.chunks, retriever_factory, self.chunks.text
            )
            self._query_is_vector = False

    # -- queries -------------------------------------------------------------

    def retrieve_query(self, query_table: Table) -> Table:
        """``query_table(query: str, k: int[, metadata_filter: str]
        [, filepath_globpattern: str])`` -> ``result`` column: tuple of
        ``{"text", "metadata", "dist"}`` dicts (reference DocumentStore
        retrieve format :188-211).

        ``metadata_filter`` is a JMESPath-subset expression over each
        chunk's metadata (globmatch/contains supported,
        internals/jmespath_lite.py); ``filepath_globpattern`` glob-matches
        the metadata ``path`` field. Filtered retrieval over-fetches
        (3k + 10 candidates) before filtering, like the reference's
        filter-aware index wrapper (external_integration/mod.rs:373)."""
        qcols = query_table.column_names()
        has_filters = (
            "metadata_filter" in qcols or "filepath_globpattern" in qcols
        )
        sel: dict[str, Any] = {
            "query": query_table.query,
            "k": query_table.k,
        }
        if "metadata_filter" in qcols:
            sel["metadata_filter"] = query_table.metadata_filter
        if "filepath_globpattern" in qcols:
            sel["filepath_globpattern"] = query_table.filepath_globpattern
        if self._query_is_vector:
            sel["_qv"] = self.embedder(query_table.query)
        prepped = query_table.select(**sel)
        qcol = prepped["_qv"] if self._query_is_vector else prepped["query"]
        fetch_k = (
            pw_apply(lambda kk: 3 * kk + 10, prepped.k)
            if has_filters
            else prepped.k
        )
        if self._hybrid is not None:
            reply = self._hybrid.query_as_of_now(
                prepped, [qcol, prepped["query"]], number_of_matches=fetch_k
            )
            from pathway_tpu.stdlib.indexing.data_index import (
                explode_reply,
                fetch_docs_for_hits,
            )

            hits = fetch_docs_for_hits(
                self.indexed,
                prepped,
                explode_reply(reply),
                doc_columns=["text", "_metadata"],
            )
        else:
            hits = self.index.query_docs_as_of_now(
                prepped,
                qcol,
                doc_columns=["text", "_metadata"],
                number_of_matches=fetch_k,
            )

        # Map higher-is-better scores to the reference's distance scale per
        # metric (ADVICE r1): cos similarity -> 1 - sim in [0, 2]; l2sq score
        # is -distance² -> distance² = -score; dot/bm25/RRF -> -score.
        if self._hybrid is None and self._query_is_vector and self.metric == "cos":
            to_dist = lambda s: 1.0 - float(s)  # noqa: E731
        else:
            to_dist = lambda s: -float(s)  # noqa: E731

        def to_result(
            texts: tuple,
            metas: tuple,
            scores: tuple,
            kk: int,
            meta_filter=None,
            glob_pattern=None,
        ) -> tuple:
            from pathway_tpu.internals import jmespath_lite

            out = []
            for t, m, s in zip(texts, metas, scores):
                meta = dict(m.value if hasattr(m, "value") else (m or {}))
                if meta_filter:
                    try:
                        if jmespath_lite.search(meta_filter, meta) is not True:
                            continue
                    except jmespath_lite.JMESPathError:
                        continue
                if glob_pattern:
                    path = str(meta.get("path", ""))
                    if not jmespath_lite.globmatch(glob_pattern, path):
                        continue
                out.append(
                    {"text": t, "metadata": meta, "dist": to_dist(s)}
                )
                if len(out) >= kk:
                    break
            return tuple(out)

        pq = prepped.restrict(hits)
        filter_kwargs = {
            name: pq[name]
            for name in ("metadata_filter", "filepath_globpattern")
            if name in prepped.column_names()
        }
        # absent filters fall back to to_result's None defaults — no dummy
        # per-row columns
        kw_map = {
            "metadata_filter": "meta_filter",
            "filepath_globpattern": "glob_pattern",
        }
        return hits.select(
            result=pw_apply(
                to_result,
                hits["text"],
                hits["_metadata"],
                hits["_pw_index_reply_scores"],
                pq["k"],
                **{kw_map[n]: e for n, e in filter_kwargs.items()},
            )
        )

    def _broadcast_to_queries(
        self, query_table: Table, singleton: Table, **cols: Any
    ) -> Table:
        """Left-join every query row against a single aggregate row."""
        first_col = query_table.column_names()[0]
        one_q = query_table.select(
            _one=pw_apply(lambda *_a: 1, query_table[first_col])
        )
        agg_k = singleton.select(
            _one=pw_apply(lambda *_a: 1, singleton[singleton.column_names()[0]]),
            **{n: singleton[n] for n in singleton.column_names()},
        )
        joined = one_q.join_left(
            agg_k, one_q["_one"] == agg_k["_one"], id=one_q.id
        )
        return joined.select(**{n: agg_k[n] for n in cols})

    def statistics_query(self, query_table: Table) -> Table:
        """Indexed chunk count per request (reference statistics endpoint)."""
        from pathway_tpu.internals.reducers import count

        stats = self.chunks.reduce(count=count())
        return self._broadcast_to_queries(query_table, stats, count=stats.count)

    def inputs_query(self, query_table: Table) -> Table:
        """Metadata of all input documents (reference /v1/inputs)."""
        from pathway_tpu.internals.reducers import tuple as tuple_reducer

        docs = self.input_docs
        metas = docs.select(m=pw_apply(lambda m: dict(m or {}), docs["_metadata"]))
        agg = metas.reduce(result=tuple_reducer(metas.m))
        return self._broadcast_to_queries(query_table, agg, result=agg.result)
