"""Rerankers: (doc, query) -> relevance score UDFs.

Reference: xpacks/llm/rerankers.py — LLMReranker (:58), CrossEncoderReranker
(:186, sentence-transformers CE on torch), EncoderReranker (:251),
rerank_topk_filter (:15). The cross-encoder here is the TPU JAX model
(models/transformer.py cross_encode) microbatched per commit.
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

from pathway_tpu.internals.expression import apply as pw_apply
from pathway_tpu.internals.udfs import UDF, batch_executor
from pathway_tpu.xpacks.llm._tokenizer import HashTokenizer, pad_to_buckets
from pathway_tpu.xpacks.llm.embedders import _ENCODER_PRESETS


class CrossEncoderReranker(UDF):
    """TPU cross-encoder: [CLS] doc [SEP] query [SEP] -> logit.

    ``model_name`` picks the architecture preset (ms-marco-MiniLM maps to
    the MiniLM-L6 tower); weights random unless ``params`` given.
    """

    def __init__(
        self,
        model_name: str = "cross-encoder/ms-marco-TinyBERT-L-2-v2",
        *,
        max_len: int = 256,
        max_batch_size: int = 128,
        params: Any = None,
        seed: int = 0,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from pathway_tpu.models import (
            cross_encode,
            init_cross_encoder_params,
            minilm_l6,
        )

        self.config = minilm_l6()
        self.max_len = max_len
        self._tok = HashTokenizer(self.config.vocab_size)
        if params is None:
            params = init_cross_encoder_params(jax.random.key(seed), self.config)
        cfg = self.config
        # params as a runtime argument: closed-over arrays become HLO
        # constants and inflate compile times by the full weight tree
        import functools

        self._jit_score = functools.partial(
            jax.jit(lambda p, ids, mask: cross_encode(p, ids, mask, cfg)),
            params,
        )

        def score_batch(docs: list, queries: list) -> list:
            ids, mask = self._tok.encode_pair_batch(
                [str(d) for d in docs], [str(q) for q in queries], self.max_len
            )
            ids, mask, real = pad_to_buckets(ids, mask)
            scores = np.asarray(
                self._jit_score(jnp.asarray(ids), jnp.asarray(mask)), np.float32
            )
            return [float(s) for s in scores[:real]]

        super().__init__(
            score_batch,
            executor=batch_executor(max_batch_size=max_batch_size),
            deterministic=True,
        )


class EncoderReranker(UDF):
    """Bi-encoder similarity reranker (reference :251): embeds doc and query
    with the given embedder UDF's underlying model and scores by cosine."""

    def __init__(self, embedder: Any) -> None:
        inner = embedder

        def score_batch(docs: list, queries: list) -> list:
            d = inner.execute_rows([(str(x),) for x in docs])
            q = inner.execute_rows([(str(x),) for x in queries])
            out = []
            for (ok_d, dv), (ok_q, qv) in zip(d, q):
                if not (ok_d and ok_q):
                    raise RuntimeError("embedding failed in EncoderReranker")
                dv = np.asarray(dv, np.float32)
                qv = np.asarray(qv, np.float32)
                denom = np.linalg.norm(dv) * np.linalg.norm(qv)
                out.append(float(dv @ qv / max(denom, 1e-30)))
            return out

        super().__init__(
            score_batch, executor=batch_executor(), deterministic=True
        )


class LLMReranker(UDF):
    """LLM-as-judge 1-5 relevance score (reference :58)."""

    PROMPT = (
        "Given a query and a document, rate how relevant the document is to "
        "the query on a scale 1 to 5. Answer with a single digit.\n"
        "Query: {query}\nDocument: {doc}\nScore:"
    )

    def __init__(self, llm: Any) -> None:
        chat = llm

        def score_batch(docs: list, queries: list) -> list:
            prompts = [
                self.PROMPT.format(query=q, doc=d) for d, q in zip(docs, queries)
            ]
            replies = chat.execute_rows([(p,) for p in prompts])
            out = []
            for ok, text in replies:
                if not ok:
                    raise RuntimeError(f"LLM reranker call failed: {text!r}")
                m = re.search(r"[1-5]", str(text))
                out.append(float(m.group()) if m else 1.0)
            return out

        super().__init__(score_batch, executor=batch_executor())


def rerank_topk_filter(
    docs: tuple, scores: tuple, k: int = 5
) -> tuple[tuple, tuple]:
    """Keep the k best (doc, score) pairs (reference :15); an apply-ready
    helper over collapsed doc/score tuples."""
    order = sorted(range(len(docs)), key=lambda i: -scores[i])[:k]
    return (
        tuple(docs[i] for i in order),
        tuple(scores[i] for i in order),
    )
