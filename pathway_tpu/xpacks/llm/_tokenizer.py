"""Tokenization for local TPU models.

The reference delegates to HF tokenizers downloaded from the hub
(xpacks/llm/embedders.py:270). This environment has no egress, so the
default is a deterministic hashing tokenizer (stable across runs and
processes); a locally cached HF tokenizer object can be passed anywhere a
tokenizer is accepted — the contract is just ``encode_batch``.
"""

from __future__ import annotations

import hashlib
from typing import Any, Protocol, Sequence

import numpy as np

CLS_ID = 1
SEP_ID = 2


class Tokenizer(Protocol):
    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """-> (token_ids [b, t] int32, mask [b, t] bool), t <= max_len."""
        ...


def _hash_token(word: str, vocab_size: int) -> int:
    h = hashlib.blake2s(word.encode(), digest_size=4).digest()
    # ids 0..3 reserved (pad/cls/sep/unk)
    return 4 + int.from_bytes(h, "little") % (vocab_size - 4)


class HashTokenizer:
    """Whitespace+punctuation split, blake2s-hashed ids, CLS/SEP framing."""

    def __init__(self, vocab_size: int = 30522) -> None:
        self.vocab_size = vocab_size

    def _words(self, text: str) -> list[str]:
        out, cur = [], []
        for ch in str(text).lower():
            if ch.isalnum():
                cur.append(ch)
            else:
                if cur:
                    out.append("".join(cur))
                    cur = []
                if not ch.isspace():
                    out.append(ch)
        if cur:
            out.append("".join(cur))
        return out

    def encode(self, text: str, max_len: int) -> list[int]:
        words = self._words(text)[: max_len - 2]
        return (
            [CLS_ID]
            + [_hash_token(w, self.vocab_size) for w in words]
            + [SEP_ID]
        )

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        encoded = [self.encode(t, max_len) for t in texts]
        t = max((len(e) for e in encoded), default=2)
        ids = np.zeros((len(texts), t), np.int32)
        mask = np.zeros((len(texts), t), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        return ids, mask

    def encode_pair_batch(
        self, left: Sequence[str], right: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """[CLS] left [SEP] right [SEP] — the cross-encoder input shape."""
        texts = []
        encoded = []
        for l_txt, r_txt in zip(left, right):
            lw = self._words(l_txt)
            rw = self._words(r_txt)
            budget = max_len - 3
            lw = lw[: budget // 2]
            rw = rw[: budget - len(lw)]
            encoded.append(
                [CLS_ID]
                + [_hash_token(w, self.vocab_size) for w in lw]
                + [SEP_ID]
                + [_hash_token(w, self.vocab_size) for w in rw]
                + [SEP_ID]
            )
        t = max((len(e) for e in encoded), default=3)
        ids = np.zeros((len(encoded), t), np.int32)
        mask = np.zeros((len(encoded), t), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self._words(text))

    def decode(self, ids: Sequence[int]) -> str:
        return " ".join(f"<{i}>" for i in ids if i > 3)


def pad_to_buckets(
    ids: np.ndarray, mask: np.ndarray, batch_bucket_min: int = 8
) -> tuple[np.ndarray, np.ndarray, int]:
    """Pad batch and seq dims up to powers of two so jit caches stay small.

    Returns (ids, mask, real_batch). Sequence is padded to the next power of
    two; batch likewise (min ``batch_bucket_min``).
    """
    b, t = ids.shape
    bt = batch_bucket_min
    while bt < b:
        bt *= 2
    tt = 8
    while tt < t:
        tt *= 2
    out_ids = np.zeros((bt, tt), np.int32)
    out_mask = np.zeros((bt, tt), bool)
    out_ids[:b, :t] = ids
    out_mask[:b, :t] = mask
    return out_ids, out_mask, b
