"""Tokenization for local TPU models.

The reference delegates to HF tokenizers downloaded from the hub
(xpacks/llm/embedders.py:270). This environment has no egress, so the
default is a deterministic hashing tokenizer (stable across runs and
processes); a locally cached HF tokenizer object can be passed anywhere a
tokenizer is accepted — the contract is just ``encode_batch``.
"""

from __future__ import annotations

import functools
import hashlib
import re
from typing import Any, Protocol, Sequence

import numpy as np

CLS_ID = 1
SEP_ID = 2


class Tokenizer(Protocol):
    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """-> (token_ids [b, t] int32, mask [b, t] bool), t <= max_len."""
        ...


def _is_cjk(ch: str) -> bool:
    cp = ord(ch)
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0x2A700 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF
        or 0x2F800 <= cp <= 0x2FA1F
    )


@functools.lru_cache(maxsize=1 << 16)
def _hash_token(word: str, vocab_size: int) -> int:
    # word frequencies are Zipfian, so the cache absorbs nearly every
    # lookup on real text (the blake2s+mod was ~25% of ingest CPU)
    h = hashlib.blake2s(word.encode(), digest_size=4).digest()
    # ids 0..3 reserved (pad/cls/sep/unk)
    return 4 + int.from_bytes(h, "little") % (vocab_size - 4)


#: alnum runs become words; any other non-space character is its own token
#: (C-speed equivalent of the former per-character isalnum() scan, which
#: dominated ingest profiles at ~0.5 s per 7k docs)
_WORD_RE = re.compile(r"[^\W_]+|[^\w\s]|_")


class HashTokenizer:
    """Whitespace+punctuation split, blake2s-hashed ids, CLS/SEP framing."""

    #: id 0 is reserved for padding (encode_batch zero-fills)
    pad_id = 0

    def __init__(self, vocab_size: int = 30522) -> None:
        self.vocab_size = vocab_size

    def _words(self, text: str) -> list[str]:
        return _WORD_RE.findall(str(text).lower())

    def encode(self, text: str, max_len: int) -> list[int]:
        words = self._words(text)[: max_len - 2]
        return (
            [CLS_ID]
            + [_hash_token(w, self.vocab_size) for w in words]
            + [SEP_ID]
        )

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        encoded = [self.encode(t, max_len) for t in texts]
        t = max((len(e) for e in encoded), default=2)
        ids = np.zeros((len(texts), t), np.int32)
        mask = np.zeros((len(texts), t), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        return ids, mask

    def encode_pair_batch(
        self, left: Sequence[str], right: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """[CLS] left [SEP] right [SEP] — the cross-encoder input shape."""
        texts = []
        encoded = []
        for l_txt, r_txt in zip(left, right):
            lw = self._words(l_txt)
            rw = self._words(r_txt)
            budget = max_len - 3
            lw = lw[: budget // 2]
            rw = rw[: budget - len(lw)]
            encoded.append(
                [CLS_ID]
                + [_hash_token(w, self.vocab_size) for w in lw]
                + [SEP_ID]
                + [_hash_token(w, self.vocab_size) for w in rw]
                + [SEP_ID]
            )
        t = max((len(e) for e in encoded), default=3)
        ids = np.zeros((len(encoded), t), np.int32)
        mask = np.zeros((len(encoded), t), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        return ids, mask

    def count_tokens(self, text: str) -> int:
        return len(self._words(text))

    def decode(self, ids: Sequence[int]) -> str:
        return " ".join(f"<{i}>" for i in ids if i > 3)


def pad_to_buckets(
    ids: np.ndarray,
    mask: np.ndarray,
    batch_bucket_min: int = 8,
    seq_bucket_min: int = 8,
) -> tuple[np.ndarray, np.ndarray, int]:
    """Pad batch and seq dims up to powers of two so jit caches stay small.

    Returns (ids, mask, real_batch). Sequence is padded to the next power of
    two (min ``seq_bucket_min`` — raise it to trade padding FLOPs for fewer
    jit specializations, e.g. on remote-device links where each compile is
    expensive); batch likewise (min ``batch_bucket_min``).
    """
    b, t = ids.shape
    bt = batch_bucket_min
    while bt < b:
        bt *= 2
    tt = seq_bucket_min
    while tt < t:
        tt *= 2
    out_ids = np.zeros((bt, tt), np.int32)
    out_mask = np.zeros((bt, tt), bool)
    out_ids[:b, :t] = ids
    out_mask[:b, :t] = mask
    return out_ids, out_mask, b


class WordPieceTokenizer:
    """BERT WordPiece over a real vocab (reference models load HF
    tokenizers, embedders.py:270; this is the native implementation of the
    same algorithm: basic tokenization, then greedy longest-match-first
    subwords with ``##`` continuations).

    ``vocab``: path to a vocab.txt (one token per line, HF layout) or a
    dict token -> id. Special tokens follow BERT conventions.
    """

    def __init__(
        self,
        vocab: "str | dict[str, int]",
        *,
        lowercase: bool = True,
        unk_token: str = "[UNK]",
        cls_token: str = "[CLS]",
        sep_token: str = "[SEP]",
        pad_token: str = "[PAD]",
        max_chars_per_word: int = 100,
    ) -> None:
        if isinstance(vocab, str):
            with open(vocab, encoding="utf-8") as f:
                vocab = {line.rstrip("\n"): i for i, line in enumerate(f)}
        self.vocab = dict(vocab)
        self.ids_to_tokens = {i: t for t, i in self.vocab.items()}
        self.lowercase = lowercase
        self.unk_id = self.vocab[unk_token]
        self.cls_id = self.vocab[cls_token]
        self.sep_id = self.vocab[sep_token]
        self.pad_id = self.vocab[pad_token]
        self._special_tokens = {cls_token, sep_token, pad_token}
        self.max_chars_per_word = max_chars_per_word
        self.vocab_size = max(self.vocab.values()) + 1

    # -- basic tokenization (BERT BasicTokenizer) ----------------------------

    def _basic_tokens(self, text: str) -> list[str]:
        import unicodedata

        if self.lowercase:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(
                c for c in text if unicodedata.category(c) != "Mn"
            )
        out: list[str] = []
        word: list[str] = []

        def flush() -> None:
            if word:
                out.append("".join(word))
                word.clear()

        for ch in text:
            cat = unicodedata.category(ch)
            if cat in ("Cc", "Cf") and ch not in ("\t", "\n", "\r"):
                continue  # strip control chars (BERT BasicTokenizer)
            if ch.isspace():
                flush()
            elif _is_cjk(ch):
                # every CJK character is its own token, as in HF's
                # BasicTokenizer — multilingual vocabs are built that way
                flush()
                out.append(ch)
            elif cat.startswith("P") or ch in "$+<=>^`|~":
                flush()
                out.append(ch)
            else:
                word.append(ch)
        flush()
        return out

    # -- wordpiece ------------------------------------------------------------

    def _wordpiece(self, word: str) -> list[int]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str, max_len: int | None = None) -> list[int]:
        ids = [self.cls_id]
        for word in self._basic_tokens(str(text)):
            ids.extend(self._wordpiece(word))
        budget = (max_len - 1) if max_len is not None else None
        if budget is not None and len(ids) > budget:
            ids = ids[:budget]
        ids.append(self.sep_id)
        return ids

    def encode_batch(
        self, texts: Sequence[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        encoded = [self.encode(t, max_len) for t in texts]
        t = max(len(e) for e in encoded) if encoded else 1
        ids = np.full((len(encoded), t), self.pad_id, np.int32)
        mask = np.zeros((len(encoded), t), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        return ids, mask

    def decode(self, ids: Sequence[int]) -> str:
        words: list[str] = []
        for i in ids:
            tok = self.ids_to_tokens.get(int(i), "")
            if tok in self._special_tokens:
                continue
            if tok.startswith("##") and words:
                words[-1] += tok[2:]
            else:
                words.append(tok)
        return " ".join(words)
