"""Chat models (reference: xpacks/llm/llms.py).

The local chat — reference HFPipelineChat (:441, torch `pipeline`) — is the
TPU-native causal decoder (models/decoder.py): greedy decode with a static
KV cache, microbatched by the engine. Remote chats (OpenAIChat :84,
LiteLLMChat :313, CohereChat :544) are async UDFs over an injected client
(zero-egress environment).
"""

from __future__ import annotations

import json
from typing import Any, Callable

from pathway_tpu.internals.udfs import (
    UDF,
    AsyncRetryStrategy,
    CacheStrategy,
    async_executor,
    batch_executor,
)
from pathway_tpu.xpacks.llm._tokenizer import HashTokenizer


def _checkpoint_digest(params: Any, tokenizer: Any) -> str:
    """Stable fingerprint of a custom (params, tokenizer) pair, so a
    persistent UDF cache survives restarts and distinguishes checkpoints
    (ADVICE r2: ``id(self)`` changed per run and could repeat after gc).

    Per leaf: tree path + shape + dtype + a 16-element head sample + a
    whole-tensor float32 sum. Samples and sums ride ONE fused device
    reduction and ONE device→host fetch (per-leaf fetches would cost a
    tunnel RTT each at init) — a fine-tune that changes any weight
    anywhere moves its leaf sum, without downloading the full tree."""
    import hashlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    h = hashlib.blake2b(digest_size=8)
    if params is not None:
        leaves = sorted(
            jax.tree_util.tree_flatten_with_path(params)[0],
            key=lambda kv: str(kv[0]),
        )

        def fingerprint(ls):
            rows = []
            for x in ls:
                flat = jnp.ravel(x).astype(jnp.float32)
                head = jnp.zeros((16,), jnp.float32)
                head = head.at[: min(16, flat.size)].set(flat[:16])
                rows.append(jnp.concatenate([head, jnp.sum(flat)[None]]))
            return jnp.stack(rows)

        prints = np.asarray(
            jax.jit(fingerprint)([leaf for _p, leaf in leaves])
        )
        for (path, leaf), row in zip(leaves, prints):
            h.update(str(path).encode())
            h.update(str(jnp.shape(leaf)).encode())
            h.update(str(jnp.result_type(leaf)).encode())
            h.update(np.ascontiguousarray(row).tobytes())
    if tokenizer is not None:
        h.update(type(tokenizer).__name__.encode())
        vocab = getattr(tokenizer, "vocab", None)
        if vocab is not None:
            vocab_list = list(vocab)
            h.update(str(len(vocab_list)).encode())
            for tok in vocab_list[:8] + vocab_list[-8:]:
                h.update(str(tok).encode())
    return h.hexdigest()


class TpuPipelineChat(UDF):
    """Local decode on TPU.

    ``model`` picks a DecoderConfig preset ('mistral-7b' or 'tiny'); weights
    random unless ``params`` is passed (import a checkpoint for real text).
    A custom tokenizer with ``encode``/``decode`` may be supplied.
    """

    def __init__(
        self,
        model: str = "tiny",
        *,
        max_new_tokens: int = 32,
        max_prompt_len: int = 128,
        params: Any = None,
        tokenizer: Any = None,
        seed: int = 0,
        max_batch_size: int = 8,
        cache_tag: str | None = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int | None = None,
        top_p: float | None = None,
    ) -> None:
        import zlib

        import jax
        import jax.numpy as jnp
        import numpy as np

        from pathway_tpu.models import (
            greedy_generate,
            init_decoder_params,
            mistral_7b,
            sample_generate,
            tiny_decoder,
        )

        cfg_fn = {"mistral-7b": mistral_7b, "tiny": tiny_decoder}.get(model)
        if cfg_fn is None:
            raise ValueError(f"unknown decoder preset {model!r}")
        self.config = cfg_fn()
        self.max_new_tokens = max_new_tokens
        self.max_prompt_len = max_prompt_len
        self.tokenizer = tokenizer or HashTokenizer(self.config.vocab_size)
        custom_weights = params is not None or tokenizer is not None
        if params is None:
            params = init_decoder_params(jax.random.key(seed), self.config)
        cfg = self.config
        mnt = max_new_tokens

        def generate_batch(prompts: list) -> list:
            texts = [_coerce_prompt(p) for p in prompts]
            encoded = [
                self.tokenizer.encode(t, self.max_prompt_len) for t in texts
            ]
            t_max = max(len(e) for e in encoded)
            ids = np.zeros((len(texts), t_max), np.int32)
            mask = np.zeros((len(texts), t_max), bool)
            for i, e in enumerate(encoded):
                ids[i, t_max - len(e) :] = e  # left-pad: generation is at end
                mask[i, t_max - len(e) :] = True
            if do_sample:
                # per-row seed from (seed, prompt text): sampling stays a
                # deterministic function of the row, independent of batch
                # composition (retraction consistency)
                row_seeds = np.asarray(
                    [
                        (zlib.crc32(t.encode()) ^ seed) & 0xFFFFFFFF
                        for t in texts
                    ],
                    np.uint32,
                )
                toks = sample_generate(
                    params,
                    jnp.asarray(ids),
                    cfg,
                    max_new_tokens=mnt,
                    row_seeds=jnp.asarray(row_seeds),
                    temperature=temperature,
                    top_k=top_k,
                    top_p=top_p,
                    eos_id=2,
                    prompt_mask=jnp.asarray(mask),
                )
            else:
                toks = greedy_generate(
                    params,
                    jnp.asarray(ids),
                    cfg,
                    max_new_tokens=mnt,
                    eos_id=2,
                    prompt_mask=jnp.asarray(mask),
                )
            toks = np.asarray(toks)
            return [self.tokenizer.decode(list(row)) for row in toks]

        super().__init__(
            generate_batch,
            executor=batch_executor(max_batch_size=max_batch_size),
            deterministic=True,
            # sampling params only shape the output when do_sample is on;
            # keeping them out of the greedy name preserves existing caches.
            # Custom params/tokenizer change generations: without an explicit
            # cache_tag they get a content-derived namespace (stable across
            # restarts) so two checkpoints can never serve each other's
            # cached rows.
            cache_name=(
                f"TpuPipelineChat:{model}:{max_new_tokens}:{max_prompt_len}"
                f":seed{seed}"
                + (
                    f":tag{cache_tag}"
                    if cache_tag is not None
                    else (
                        f":ckpt{_checkpoint_digest(params, tokenizer)}"
                        if custom_weights
                        else ""
                    )
                )
                + (
                    f":sample:{temperature}:{top_k}:{top_p}"
                    if do_sample
                    else ""
                )
            ),
        )


class HFPipelineChat(TpuPipelineChat):
    """Reference-compatible name (llms.py:441); decode runs on TPU."""


def _coerce_prompt(prompt: Any) -> str:
    """Accept plain strings or OpenAI-style message lists."""
    if isinstance(prompt, str):
        try:
            parsed = json.loads(prompt)
        except (json.JSONDecodeError, ValueError):
            return prompt
        prompt = parsed
    if isinstance(prompt, (list, tuple)):
        return "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in prompt
            if isinstance(m, dict)
        )
    return str(prompt)


class _RemoteChat(UDF):
    def __init__(
        self,
        model: str,
        client: Callable[..., Any] | None = None,
        *,
        capacity: int | None = None,
        timeout: float | None = None,
        cache_strategy: CacheStrategy | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        **client_kwargs: Any,
    ) -> None:
        self.model = model
        self.kwargs = client_kwargs
        if client is None:
            raise ValueError(
                f"{type(self).__name__} needs an async `client` callable "
                "(no network egress here); use xpacks.llm.mocks for tests"
            )

        async def call(prompt: Any) -> str:
            result = client(model=self.model, prompt=prompt, **self.kwargs)
            if hasattr(result, "__await__"):
                result = await result
            return str(result)

        super().__init__(
            call,
            executor=async_executor(capacity=capacity, timeout=timeout),
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
            cache_name=f"{type(self).__name__}:{model}",
        )


class OpenAIChat(_RemoteChat):
    """Reference: llms.py:84."""

    def __init__(self, model: str = "gpt-4o-mini", **kw: Any):
        super().__init__(model, **kw)


class LiteLLMChat(_RemoteChat):
    """Reference: llms.py:313."""

    def __init__(self, model: str = "", **kw: Any):
        super().__init__(model, **kw)


class CohereChat(_RemoteChat):
    """Reference: llms.py:544."""

    def __init__(self, model: str = "command", **kw: Any):
        super().__init__(model, **kw)


def prompt_chat_single_qa(question: str) -> str:
    """Wrap a question as a single-turn message list (reference llms.py:686)."""
    return json.dumps([{"role": "user", "content": str(question)}])
