"""REST servers for document stores and RAG pipelines.

Reference: xpacks/llm/servers.py — BaseRestServer (:16),
DocumentStoreServer (:92), QARestServer (:140). Routes are rest_connector
pairs (io/http.py); the whole app is one streaming dataflow run.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.runner import GraphRunner
from pathway_tpu.internals.table import Table
from pathway_tpu.io.http import PathwayWebserver, rest_connector


class ServerHandle:
    def __init__(self, runner: GraphRunner, thread: threading.Thread | None):
        self.runner = runner
        self.thread = thread

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()


class BaseRestServer:
    def __init__(self, host: str, port: int, **kwargs: Any) -> None:
        self.host = host
        self.port = port
        self.webserver = PathwayWebserver(host, port)
        self._routes: list[tuple[Table, Callable]] = []

    def serve(
        self,
        route: str,
        schema: schema_mod.SchemaMetaclass,
        handler: Callable[[Table], Table],
        **kwargs: Any,
    ) -> None:
        query_table, attach = rest_connector(
            schema=schema, route=route, webserver=self.webserver, **kwargs
        )
        result = handler(query_table)
        self._routes.append((result, attach))

    def run(
        self, *, threaded: bool = False, with_cache: bool = False
    ) -> ServerHandle:
        """Build the dataflow, open the port, run the streaming loop."""
        if with_cache:
            # UDF-level caches (DiskCache) already persist under
            # PATHWAY_TPU_UDF_CACHE; nothing extra to wire here yet.
            pass
        runner = GraphRunner()
        for result, attach in self._routes:
            attach(result, runner)
        if threaded:
            thread = threading.Thread(
                target=runner.run, name="pw-server-run", daemon=True
            )
            thread.start()
            return ServerHandle(runner, thread)
        handle = ServerHandle(runner, None)
        runner.run()
        return handle


class DocumentStoreServer(BaseRestServer):
    """/v1/retrieve, /v1/statistics, /v1/inputs (reference :92)."""

    def __init__(self, host: str, port: int, document_store: Any) -> None:
        super().__init__(host, port)
        store = document_store
        retrieve_schema = schema_mod.schema_from_dict(
            {"query": dt.STR, "k": dt.INT}, name="RetrieveQuerySchema"
        )
        empty_schema = schema_mod.schema_from_dict(
            {}, name="EmptyQuerySchema"
        )
        self.serve("/v1/retrieve", retrieve_schema, store.retrieve_query)
        self.serve("/v1/statistics", empty_schema, store.statistics_query)
        self.serve("/v1/inputs", empty_schema, store.inputs_query)


class QARestServer(BaseRestServer):
    """/v1/pw_ai_answer (+ retrieval passthrough) (reference :140)."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any) -> None:
        super().__init__(host, port)
        rag = rag_question_answerer
        answer_schema = schema_mod.schema_from_dict(
            {"prompt": dt.STR}, name="QASchema"
        )
        retrieve_schema = schema_mod.schema_from_dict(
            {"query": dt.STR, "k": dt.INT}, name="RetrieveQuerySchema"
        )
        self.serve("/v1/pw_ai_answer", answer_schema, rag.answer_query)
        self.serve(
            "/v1/retrieve", retrieve_schema, rag.indexer.retrieve_query
        )


class QASummaryRestServer(QARestServer):
    """Adds /v1/pw_ai_summary (reference :193)."""

    def __init__(self, host: str, port: int, rag: Any) -> None:
        super().__init__(host, port, rag)
        summary_schema = schema_mod.schema_from_dict(
            {"text_list": dt.ANY}, name="SummarySchema"
        )
        self.serve("/v1/pw_ai_summary", summary_schema, rag.summarize_query)
