"""Offline fake models for tests (reference: xpacks/llm/tests/mocks.py:5-24)."""

from __future__ import annotations

import hashlib
from typing import Any

import numpy as np

from pathway_tpu.internals.udfs import UDF, SyncExecutor


def fake_embeddings_model(text: str, dim: int = 16) -> np.ndarray:
    """Deterministic unit vector from a text hash."""
    seed = int.from_bytes(
        hashlib.blake2s(str(text).encode(), digest_size=8).digest(), "little"
    )
    rng = np.random.default_rng(seed)
    v = rng.normal(size=dim).astype(np.float32)
    return v / np.linalg.norm(v)


class FakeEmbedder(UDF):
    def __init__(self, dim: int = 16) -> None:
        self.dim = dim

        def embed(text: str) -> np.ndarray:
            return fake_embeddings_model(text, self.dim)

        super().__init__(embed, executor=SyncExecutor(), deterministic=True)

    def get_embedding_dimension(self) -> int:
        return self.dim


class IdentityMockChat(UDF):
    """Echoes 'model: prompt' (reference mocks.py IdentityMockChat)."""

    def __init__(self, model: str = "mock") -> None:
        self.model = model

        def chat(prompt: Any) -> str:
            return f"{self.model}: {prompt}"

        super().__init__(chat, executor=SyncExecutor(), deterministic=True)


class FakeChatModel(UDF):
    """Always answers with a canned string (reference mocks.py FakeChatModel)."""

    def __init__(self, answer: str = "Text") -> None:
        def chat(prompt: Any) -> str:
            return answer

        super().__init__(chat, executor=SyncExecutor(), deterministic=True)
