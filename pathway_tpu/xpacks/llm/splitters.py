"""Document splitters (reference: xpacks/llm/splitters.py).

TokenCountSplitter (:34) uses tiktoken in the reference; token counting here
uses the same tokenizer family as the local models (HashTokenizer word
units), which keeps chunk budgets aligned with what the TPU encoder sees.
Returns ``tuple[(chunk_text, metadata_dict)]`` like the reference.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.udfs import UDF, SyncExecutor
from pathway_tpu.xpacks.llm._tokenizer import HashTokenizer


class TokenCountSplitter(UDF):
    """Greedy sentence-ish packing between min_tokens and max_tokens."""

    def __init__(
        self, min_tokens: int = 50, max_tokens: int = 500, encoding_name: str = ""
    ) -> None:
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self._tok = HashTokenizer()

        def split(text: str, metadata: dict | None = None) -> tuple:
            meta = dict(metadata or {})
            words = str(text).split()
            chunks: list[tuple[str, dict]] = []
            cur: list[str] = []
            count = 0
            for word in words:
                n = max(1, self._tok.count_tokens(word))
                if count + n > self.max_tokens and count >= self.min_tokens:
                    chunks.append((" ".join(cur), meta))
                    cur, count = [], 0
                cur.append(word)
                count += n
            if cur:
                chunks.append((" ".join(cur), meta))
            return tuple(chunks)

        super().__init__(split, executor=SyncExecutor(), deterministic=True)


class NullSplitter(UDF):
    """Whole document as one chunk (reference: null_splitter :13)."""

    def __init__(self) -> None:
        def split(text: str, metadata: dict | None = None) -> tuple:
            return ((str(text), dict(metadata or {})),)

        super().__init__(split, executor=SyncExecutor(), deterministic=True)


def null_splitter(text: str, metadata: dict | None = None) -> tuple:
    return ((str(text), dict(metadata or {})),)
