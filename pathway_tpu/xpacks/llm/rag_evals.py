"""RAG evaluation harness — labeled QA datasets scored offline.

Reference: integration_tests/rag_evals/{evaluator,experiment}.py — a
labeled question/answer dataset driven through a RAG app and scored with
RAGAS. RAGAS needs judge LLMs and network; this build scores with the
judge-free metric family instead (the retrieval metrics are identical in
spirit; answer metrics use SQuAD-style normalized token overlap):

- ``answer_exact_match`` — normalized exact match of answer vs expected.
- ``answer_token_f1``    — token-level F1 (normalize, split, overlap).
- ``retrieval_hit_rate`` — fraction of questions where some retrieved
  context contains the expected answer (a judge-free context-recall).
- ``context_precision``  — fraction of retrieved docs per question that
  contain expected-answer tokens, averaged (judge-free RAGAS analog).

Datasets are lists of :class:`RagEvalSample` or a JSONL file of
``{"question": ..., "answer": ...}`` rows (``load_dataset``).
"""

from __future__ import annotations

import dataclasses
import json
import re
import string
from typing import Any, Callable, Sequence

from pathway_tpu.internals import schema as schema_mod


@dataclasses.dataclass(frozen=True)
class RagEvalSample:
    question: str
    answer: str
    #: optional substring identifying the gold document (path or content)
    source: str | None = None


def load_dataset(path: str) -> list[RagEvalSample]:
    """JSONL rows {"question", "answer"[, "source"]} -> samples."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            out.append(
                RagEvalSample(
                    question=row["question"],
                    answer=row["answer"],
                    source=row.get("source"),
                )
            )
    return out


def _normalize(text: str) -> str:
    """SQuAD-style normalization: lowercase, strip punctuation/articles."""
    text = text.lower()
    text = "".join(c if c not in string.punctuation else " " for c in text)
    text = re.sub(r"\b(a|an|the)\b", " ", text)
    return " ".join(text.split())


def token_f1(prediction: str, expected: str) -> float:
    pred = _normalize(prediction).split()
    gold = _normalize(expected).split()
    if not pred or not gold:
        return float(pred == gold)
    common: dict[str, int] = {}
    for tok in gold:
        common[tok] = common.get(tok, 0) + 1
    overlap = 0
    for tok in pred:
        if common.get(tok, 0) > 0:
            common[tok] -= 1
            overlap += 1
    if overlap == 0:
        return 0.0
    precision = overlap / len(pred)
    recall = overlap / len(gold)
    return 2 * precision * recall / (precision + recall)


def exact_match(prediction: str, expected: str) -> float:
    return float(_normalize(prediction) == _normalize(expected))


@dataclasses.dataclass
class RagEvalReport:
    n_samples: int
    answer_exact_match: float
    answer_token_f1: float
    retrieval_hit_rate: float
    context_precision: float
    per_sample: list[dict]
    #: samples the pipeline never answered (no result row for the
    #: question) — zero-scored AND surfaced, so silently dropped rows
    #: can't masquerade as model mistakes
    n_missing: int = 0

    def as_dict(self) -> dict:
        return {
            "n_samples": self.n_samples,
            "n_missing": self.n_missing,
            "answer_exact_match": round(self.answer_exact_match, 4),
            "answer_token_f1": round(self.answer_token_f1, 4),
            "retrieval_hit_rate": round(self.retrieval_hit_rate, 4),
            "context_precision": round(self.context_precision, 4),
        }

    def to_markdown(self) -> str:
        head = self.as_dict()
        lines = [
            "| metric | value |",
            "|---|---|",
            *(f"| {k} | {v} |" for k, v in head.items()),
        ]
        return "\n".join(lines)


class RagEvaluator:
    """Drive a question answerer over a labeled dataset and score it.

    ``answerer`` is any object with the BaseRAGQuestionAnswerer contract:
    ``answer_query(table(prompt)) -> table(result, context_docs)``. The
    harness builds the query table, runs the dataflow to completion, and
    scores answers + retrieved contexts per sample (reference
    rag_evals/evaluator.py drives the app's REST API; here the dataflow
    runs in-process, which also makes the harness usable in CI).
    """

    def __init__(self, answerer: Any) -> None:
        self.answerer = answerer

    def _run(self, samples: Sequence[RagEvalSample]) -> list[tuple]:
        import pathway_tpu as pw
        from pathway_tpu.internals.runner import GraphRunner

        queries = pw.debug.table_from_rows(
            schema_mod.schema_from_types(prompt=str),
            [(s.question,) for s in samples],
        )
        result = self.answerer.answer_query(queries)
        with_prompt = result.select(
            prompt=queries.restrict(result).prompt,
            result=result.result,
            context_docs=result.context_docs,
        )
        (snap,) = GraphRunner().capture(with_prompt)
        return list(snap.values())

    @staticmethod
    def _doc_text(doc: Any) -> str:
        if isinstance(doc, dict):
            return str(doc.get("text", doc))
        return str(doc)

    def evaluate(self, samples: Sequence[RagEvalSample]) -> RagEvalReport:
        rows = self._run(samples)
        by_prompt = {prompt: (res, docs) for prompt, res, docs in rows}
        per_sample = []
        n_missing = 0
        for s in samples:
            missing = s.question not in by_prompt
            if missing:
                n_missing += 1
            res, docs = by_prompt.get(s.question, ("", ()))
            res = str(res or "")  # a None/errored answer scores 0, not crash
            docs = list(docs or ())
            gold_tokens = set(_normalize(s.answer).split())
            needle = _normalize(s.source or s.answer)
            texts = [_normalize(self._doc_text(d)) for d in docs]
            hit = any(needle in t for t in texts)
            relevant = [
                t for t in texts if gold_tokens & set(t.split())
            ]
            per_sample.append(
                {
                    "question": s.question,
                    "answer": res,
                    "expected": s.answer,
                    "exact_match": exact_match(res, s.answer),
                    "token_f1": token_f1(res, s.answer),
                    "retrieval_hit": float(hit),
                    "context_precision": (
                        len(relevant) / len(texts) if texts else 0.0
                    ),
                    "missing": missing,
                }
            )
        n = len(per_sample) or 1

        def mean(key: str) -> float:
            return sum(p[key] for p in per_sample) / n

        return RagEvalReport(
            n_samples=len(per_sample),
            answer_exact_match=mean("exact_match"),
            answer_token_f1=mean("token_f1"),
            retrieval_hit_rate=mean("retrieval_hit"),
            context_precision=mean("context_precision"),
            per_sample=per_sample,
            n_missing=n_missing,
        )


def run_experiment(
    make_answerer: Callable[..., Any],
    samples: Sequence[RagEvalSample],
    configs: Sequence[dict],
) -> list[dict]:
    """Reference experiment.py shape: evaluate a family of configurations
    (e.g. topk sweeps) and return one scored row per config."""
    out = []
    for config in configs:
        report = RagEvaluator(make_answerer(**config)).evaluate(samples)
        out.append({**config, **report.as_dict()})
    return out
