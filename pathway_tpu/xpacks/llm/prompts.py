"""Prompt templates (reference: xpacks/llm/prompts.py)."""

from __future__ import annotations

from typing import Sequence


def prompt_qa(
    query: str,
    docs: Sequence[str],
    information_not_found_response: str = "No information found.",
) -> str:
    """Short-answer RAG prompt (reference prompts.py prompt_qa)."""
    context = "\n\n".join(str(d) for d in docs)
    return (
        "Use the below articles to answer the subsequent question. If the "
        "answer cannot be found in the articles, write "
        f'"{information_not_found_response}".\n\n'
        f"Articles:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


def prompt_citing_qa(query: str, docs: Sequence[str]) -> str:
    context = "\n\n".join(f"[{i+1}] {d}" for i, d in enumerate(docs))
    return (
        "Answer the question using the sources below; cite sources as "
        f"[n].\n\nSources:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


def prompt_summarize(texts: Sequence[str]) -> str:
    joined = "\n".join(str(t) for t in texts)
    return f"Summarize the following texts briefly:\n\n{joined}\n\nSummary:"
