"""Document parsers (reference: xpacks/llm/parsers.py).

ParseUtf8 (:53) is the core path; heavy-dependency parsers
(ParseUnstructured :79, OpenParse :235, ImageParser :396, SlideParser :569,
PypdfParser :746) are gated on their optional libraries, matching the
reference's import-on-use behavior.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.udfs import UDF, SyncExecutor


class ParseUtf8(UDF):
    """bytes/str -> ((text, metadata),) — the identity document parser."""

    def __init__(self) -> None:
        def parse(contents: Any) -> tuple:
            if isinstance(contents, bytes):
                text = contents.decode("utf-8", errors="replace")
            else:
                text = str(contents)
            return ((text, {}),)

        super().__init__(parse, executor=SyncExecutor(), deterministic=True)


class Utf8Parser(ParseUtf8):
    """Newer reference alias."""


def _gated(name: str, dep: str) -> type:
    class _Gated(UDF):
        def __init__(self, *a: Any, **kw: Any) -> None:
            raise ImportError(
                f"{name} requires the optional dependency {dep!r}, which is "
                f"not available in this environment; use ParseUtf8 or "
                f"pre-extract text upstream"
            )

    _Gated.__name__ = name
    return _Gated


ParseUnstructured = _gated("ParseUnstructured", "unstructured")
OpenParse = _gated("OpenParse", "openparse")
ImageParser = _gated("ImageParser", "openai-vision")
SlideParser = _gated("SlideParser", "openai-vision")
PypdfParser = _gated("PypdfParser", "pypdf")
