"""Document parsers (reference: xpacks/llm/parsers.py).

ParseUtf8 (:53) is the core path; heavy-dependency parsers
(ParseUnstructured :79, OpenParse :235, ImageParser :396, SlideParser :569,
PypdfParser :746) are gated on their optional libraries, matching the
reference's import-on-use behavior.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.udfs import UDF, SyncExecutor


class ParseUtf8(UDF):
    """bytes/str -> ((text, metadata),) — the identity document parser."""

    def __init__(self) -> None:
        def parse(contents: Any) -> tuple:
            if isinstance(contents, bytes):
                text = contents.decode("utf-8", errors="replace")
            else:
                text = str(contents)
            return ((text, {}),)

        super().__init__(parse, executor=SyncExecutor(), deterministic=True)


class Utf8Parser(ParseUtf8):
    """Newer reference alias."""


def _gated(name: str, dep: str) -> type:
    class _Gated(UDF):
        def __init__(self, *a: Any, **kw: Any) -> None:
            raise ImportError(
                f"{name} requires the optional dependency {dep!r}, which is "
                f"not available in this environment; use ParseUtf8 or "
                f"pre-extract text upstream"
            )

    _Gated.__name__ = name
    return _Gated


ParseUnstructured = _gated("ParseUnstructured", "unstructured")
OpenParse = _gated("OpenParse", "openparse")


class PypdfParser(UDF):
    """PDF bytes -> ((page_text, metadata),) (reference PypdfParser
    parsers.py:746). Uses the native extractor in ``_pdf.py`` — covers
    machine-generated PDFs with Flate text streams; scanned decks need the
    vision path."""

    def __init__(self, apply_text_cleanup: bool = True) -> None:
        from pathway_tpu.xpacks.llm._pdf import extract_pdf_text

        def parse(contents: Any) -> tuple:
            data = (
                contents
                if isinstance(contents, bytes)
                else str(contents).encode("latin-1", errors="replace")
            )
            text = extract_pdf_text(data)
            if apply_text_cleanup:
                text = "\n".join(
                    line.strip() for line in text.splitlines() if line.strip()
                )
            return ((text, {"format": "pdf"}),)

        super().__init__(parse, executor=SyncExecutor(), deterministic=True)


_shared_vision_encoder: Any = None


def _default_vision_encoder():
    """Lazy shared TpuImageEmbedder backing the parsers' vision seam when
    no vision LLM is injected (preset via PATHWAY_VISION_PRESET; vit-b16
    — the CLIP image-tower shape — by default). One instance serves every
    parser so the ViT compiles once per process."""
    global _shared_vision_encoder
    if _shared_vision_encoder is None:
        import os

        from pathway_tpu.xpacks.llm.embedders import TpuImageEmbedder

        _shared_vision_encoder = TpuImageEmbedder(
            model=os.environ.get("PATHWAY_VISION_PRESET", "vit-b16"),
            device_resident=False,
        )
    return _shared_vision_encoder


def _vision_parts(images: list, metas: list, vision: Any) -> list:
    """Embed PIL images with the ViT in ONE batched forward: each vector
    lands in its metadata (the multimodal retrieval payload) and the text
    part carries a content signature, so downstream text remains
    content-dependent. Batched per document — a 30-page deck is one
    device dispatch, not 30."""
    import hashlib

    import numpy as np

    vecs = vision.embed_images(images)
    texts = []
    for meta, vec in zip(metas, vecs):
        meta["image_embedding"] = [float(x) for x in vec]
        sig = hashlib.blake2s(
            np.round(np.asarray(vec, np.float32), 3).tobytes(), digest_size=6
        ).hexdigest()
        texts.append(
            f"image {meta['format']} {meta['width']}x{meta['height']} "
            f"{meta['mode']} sig={sig}"
        )
    return texts


class ImageParser(UDF):
    """Image bytes -> ((description, metadata),) (reference ImageParser
    parsers.py:396: a vision LLM schema-parses the image).

    ``llm``: callable(image: PIL.Image, prompt: str) -> str — the vision
    model seam (remote vision chat in a deployment, a mock offline).
    Without it the DEFAULT is the TPU-native ViT (models/vision.py): the
    image's CLIP-style embedding lands in ``metadata["image_embedding"]``
    (the multimodal retrieval payload) and the text part carries a
    content-dependent signature. ``vision=None`` disables the encoder
    (metadata-only text, the pre-r3 behavior)."""

    def __init__(
        self,
        llm: Any = None,
        parse_prompt: str = "Describe the image contents.",
        downsize_horizontal_width: int | None = None,
        vision: Any = "default",
    ) -> None:
        import io as _io

        from PIL import Image

        def parse(contents: Any) -> tuple:
            img = Image.open(_io.BytesIO(contents))
            if (
                downsize_horizontal_width
                and img.width > downsize_horizontal_width
            ):
                ratio = downsize_horizontal_width / img.width
                img = img.resize(
                    (downsize_horizontal_width, max(1, int(img.height * ratio)))
                )
            meta = {
                "format": (img.format or "").lower(),
                "width": img.width,
                "height": img.height,
                "mode": img.mode,
            }
            if llm is not None:
                text = str(llm(img, parse_prompt))
            elif vision is not None:
                enc = (
                    _default_vision_encoder() if vision == "default" else vision
                )
                (text,) = _vision_parts([img], [meta], enc)
            else:
                text = (
                    f"image {meta['format']} {img.width}x{img.height} "
                    f"{img.mode}"
                )
            return ((text, meta),)

        super().__init__(
            parse, executor=SyncExecutor(), deterministic=llm is None
        )


class SlideParser(UDF):
    """Slide-deck images -> one (text, metadata) part per frame (reference
    SlideParser parsers.py:569 — OCR+vision over decks). Multi-frame
    images (TIFF/GIF) yield one part per page; the vision seam matches
    ImageParser."""

    def __init__(
        self,
        llm: Any = None,
        parse_prompt: str = "Describe the slide.",
        vision: Any = "default",
    ) -> None:
        import io as _io

        from PIL import Image, ImageSequence

        def parse(contents: Any) -> tuple:
            img = Image.open(_io.BytesIO(contents))
            frames, metas = [], []
            for page, frame in enumerate(ImageSequence.Iterator(img)):
                frames.append(frame.copy())
                metas.append(
                    {
                        "format": (img.format or "").lower(),
                        "page": page,
                        "width": frame.width,
                        "height": frame.height,
                        "mode": frame.mode,
                    }
                )
            if llm is not None:
                texts = [str(llm(f, parse_prompt)) for f in frames]
            elif vision is not None:
                enc = (
                    _default_vision_encoder()
                    if vision == "default"
                    else vision
                )
                # whole deck in one batched device dispatch
                texts = [
                    f"slide {m['page']}: {t}"
                    for m, t in zip(
                        metas, _vision_parts(frames, metas, enc)
                    )
                ]
            else:
                texts = [
                    f"slide {m['page']}: {m['format']} "
                    f"{m['width']}x{m['height']}"
                    for m in metas
                ]
            return tuple(zip(texts, metas))

        super().__init__(
            parse, executor=SyncExecutor(), deterministic=llm is None
        )
