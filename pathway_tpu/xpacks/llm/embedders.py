"""Embedders: text -> vector UDFs.

Reference: python/pathway/xpacks/llm/embedders.py — SentenceTransformerEmbedder
(:270, local torch), OpenAIEmbedder (:85), LiteLLMEmbedder (:180),
GeminiEmbedder (:330). The local embedder here is the TPU-native JAX encoder
(models/transformer.py) jit-compiled and driven by the engine's batch
executor, so every commit becomes one padded MXU call instead of a torch
row loop. Remote embedders are async UDFs with capacity/retry/cache knobs;
in this zero-egress environment they require an injected ``client`` callable.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.udfs import (
    UDF,
    AsyncRetryStrategy,
    CacheStrategy,
    async_executor,
    batch_executor,
)
from pathway_tpu.xpacks.llm._tokenizer import HashTokenizer, Tokenizer, pad_to_buckets

_ENCODER_PRESETS = {
    "all-MiniLM-L6-v2": "minilm_l6",
    "sentence-transformers/all-MiniLM-L6-v2": "minilm_l6",
    "BAAI/bge-base-en": "bge_base",
    "BAAI/bge-base-en-v1.5": "bge_base",
    "BAAI/bge-small-en-v1.5": "bge_small",
}


def _resolve_device_resident(device_resident: "bool | None") -> bool:
    """Shared default for the device-resident lazy-row mode (text and
    image embedders must agree on the env contract)."""
    if device_resident is not None:
        return device_resident
    import os

    return os.environ.get("PATHWAY_DEVICE_RESIDENT_UDF", "1").lower() in (
        "1",
        "true",
        "yes",
        "on",
    )


def _adaptive_sizer():
    """Device-pipeline feedback into the embed micro-batch: the adaptive
    controller can only narrow the configured ``max_batch_size``."""
    from pathway_tpu.engine import device_pipeline

    return device_pipeline.suggested_batch_size()


def _rows_from_device(vecs_dev: Any, real: int, device_resident: bool) -> list:
    """Device batch -> per-row cells: lazy device rows (prefetched host
    twin) or eager numpy."""
    if device_resident:
        from pathway_tpu.engine.device import lazy_rows

        return lazy_rows(vecs_dev, real)
    vecs = np.asarray(vecs_dev, np.float32)
    return [vecs[i] for i in range(real)]


class TpuEncoderEmbedder(UDF):
    """Local sentence embedder running on TPU.

    ``model`` picks the architecture preset (weights are randomly
    initialised unless ``params`` is given — pass imported checkpoint
    pytrees for real semantics; throughput and the full pipeline shape are
    identical either way).
    """

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        *,
        max_len: int = 128,
        max_batch_size: int = 256,
        tokenizer: Tokenizer | None = None,
        params: Any = None,
        seed: int = 0,
        cache_strategy: CacheStrategy | None = None,
        device_resident: bool | None = None,
        seq_bucket_min: int = 8,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from pathway_tpu.models import (
            bge_base,
            bge_small,
            embed,
            init_encoder_params,
            minilm_l6,
        )

        import os

        weights_tag = None
        if os.path.isdir(model):
            # locally cached HF / sentence-transformers directory: import
            # real weights + WordPiece vocab (models/hf_import.py)
            from pathway_tpu.models.hf_import import load_sentence_transformer

            if params is None or tokenizer is None:
                loaded_params, cfg, wp_tokenizer = load_sentence_transformer(
                    model
                )
                self.config = cfg
                if params is None:
                    params = loaded_params
                if tokenizer is None:
                    tokenizer = wp_tokenizer
            else:
                # both params and tokenizer given: the dir would contribute
                # nothing but a (large) deserialization — reject ambiguity
                raise ValueError(
                    "pass either a checkpoint dir or explicit "
                    "params+tokenizer, not both"
                )
            # cache key must identify the WEIGHTS, not the dir name: two
            # different checkpoints can share a basename
            import hashlib

            h = hashlib.blake2s(digest_size=8)
            for entry in sorted(os.listdir(model)):
                st = os.stat(os.path.join(model, entry))
                h.update(f"{entry}:{st.st_size}:{st.st_mtime_ns}".encode())
            weights_tag = h.hexdigest()
            preset = os.path.basename(os.path.normpath(model))
        else:
            preset = _ENCODER_PRESETS.get(model, model)
            cfg_fn = {
                "minilm_l6": minilm_l6,
                "bge_base": bge_base,
                "bge_small": bge_small,
            }.get(preset)
            if cfg_fn is None:
                raise ValueError(
                    f"unknown encoder preset {model!r}; "
                    f"known: {sorted(_ENCODER_PRESETS)} + "
                    f"minilm_l6/bge_base/bge_small, or a local checkpoint dir"
                )
            self.config = cfg_fn()
        # a checkpoint's positional table caps the usable sequence length
        self.max_len = min(max_len, self.config.max_len)
        #: minimum pow-2 seq padding bucket — raise (up to max_len) to trade
        #: padding FLOPs for fewer jit specializations (one compile per
        #: (batch bucket, seq bucket) pair; compiles are seconds-expensive
        #: over remote-device links)
        self.seq_bucket_min = min(seq_bucket_min, self.max_len)
        self.tokenizer = tokenizer or HashTokenizer(self.config.vocab_size)
        if params is None:
            params = init_encoder_params(jax.random.key(seed), self.config)
        self._params = params
        cfg = self.config
        # params ride as a runtime argument, NOT a closure: jit inlines
        # closed-over arrays as HLO constants, which bloats every bucket's
        # module with the full weight tree (measured 13-39 s per compile
        # for MiniLM-L6 vs ~2 s with params as inputs)
        import functools

        # when the tokenizer pads with id 0 (both built-ins do; bucket
        # padding is 0 too), the mask is derivable ON DEVICE as ids != 0 —
        # halving the host->device uploads per chunk. A tokenizer that
        # declares NO pad id gets the safe default (explicit mask).
        pad = getattr(
            self.tokenizer,
            "pad_id",
            getattr(self.tokenizer, "pad_token_id", None),
        )
        self._mask_from_ids = pad == 0
        if self._mask_from_ids:
            self._jit_embed_ids = functools.partial(
                jax.jit(lambda p, ids: embed(p, ids, ids != 0, cfg)), params
            )
        self._jit_embed = functools.partial(
            jax.jit(lambda p, ids, mask: embed(p, ids, mask, cfg)), params
        )

        # device-resident rows skip the device→host→device round trip
        # into the index, and lazy_rows' background prefetch overlaps
        # the host copy with the next batch's tokenize+dispatch —
        # measured ~5x cheaper per batch than the old blocking
        # np.asarray even over the remote-device tunnel (~103 ms ->
        # ~19 ms per 256-row batch). Default on; PATHWAY_DEVICE_
        # RESIDENT_UDF=0 restores eager host materialisation.
        self.device_resident = _resolve_device_resident(device_resident)

        def embed_batch(texts: list) -> list:
            ids, mask = self.tokenizer.encode_batch(
                [str(t) for t in texts], self.max_len
            )
            ids, mask, real = pad_to_buckets(
                ids, mask, seq_bucket_min=self.seq_bucket_min
            )
            if self._mask_from_ids and bool(np.array_equal(mask, ids != 0)):
                vecs_dev = self._jit_embed_ids(jnp.asarray(ids))
            else:
                vecs_dev = self._jit_embed(
                    jnp.asarray(ids), jnp.asarray(mask)
                )
            return _rows_from_device(vecs_dev, real, self.device_resident)

        super().__init__(
            embed_batch,
            executor=batch_executor(
                max_batch_size=max_batch_size, sizer=_adaptive_sizer
            ),
            deterministic=True,
            cache_strategy=cache_strategy,
            cache_name=(
                f"TpuEncoderEmbedder:{preset}:{max_len}:"
                + (f"ckpt{weights_tag}" if weights_tag else f"seed{seed}")
            ),
        )

    def get_embedding_dimension(self) -> int:
        return self.config.hidden


class SentenceTransformerEmbedder(TpuEncoderEmbedder):
    """Reference-compatible name (embedders.py:270); TPU-native engine."""


_VISION_PRESETS = {
    "vit-b16": "clip_vit_b16",
    "clip-vit-b16": "clip_vit_b16",
    "openai/clip-vit-base-patch16": "clip_vit_b16",
    "vit-tiny": "vit_tiny",
}


class TpuImageEmbedder(UDF):
    """Image bytes -> L2-normalised vector on TPU (models/vision.py ViT).

    The vision leg of the multimodal RAG path (reference: CLIP embedders
    feeding the multimodal vector store, python/pathway/xpacks/llm/
    vector_store.py:588). Weights are seeded-random unless ``params`` is
    given — embeddings are content-dependent either way (a random ViT is
    a locality-preserving projection), so retrieval pipelines measure the
    true ingest/query shape."""

    def __init__(
        self,
        model: str = "vit-b16",
        *,
        params: Any = None,
        seed: int = 0,
        max_batch_size: int = 64,
        cache_strategy: CacheStrategy | None = None,
        device_resident: bool | None = None,
    ) -> None:
        import io as _io
        import os

        import jax
        import jax.numpy as jnp

        from pathway_tpu.models.vision import (
            clip_vit_b16,
            init_vision_params,
            normalize_u8,
            preprocess_image_u8,
            vision_forward,
            vit_tiny,
        )

        preset = _VISION_PRESETS.get(model, model)
        cfg_fn = {"clip_vit_b16": clip_vit_b16, "vit_tiny": vit_tiny}.get(
            preset
        )
        if cfg_fn is None:
            raise ValueError(
                f"unknown vision preset {model!r}; known: "
                f"{sorted(_VISION_PRESETS)}"
            )
        self.config = cfg_fn()
        params_custom = params is not None
        if params is None:
            params = init_vision_params(jax.random.key(seed), self.config)
        self._params = params
        cfg = self.config
        import functools

        # uint8 pixels ride to the device; normalisation fuses into the
        # forward (4x smaller transfer than f32 pixels)
        self._jit_forward = functools.partial(
            jax.jit(lambda p, x8: vision_forward(p, normalize_u8(x8), cfg)),
            params,
        )
        self.device_resident = _resolve_device_resident(device_resident)

        def embed_batch(blobs: list) -> list:
            from PIL import Image

            pixels = np.stack(
                [
                    preprocess_image_u8(
                        Image.open(_io.BytesIO(b))
                        if isinstance(b, (bytes, bytearray))
                        else b,
                        cfg,
                    )
                    for b in blobs
                ]
            )
            return self.embed_pixels(pixels)

        if params_custom:
            # the namespace must identify the WEIGHTS (same rule as the
            # text embedder's weights_tag): a content fingerprint keeps
            # cached embeddings from different checkpoints apart
            from pathway_tpu.xpacks.llm.llms import _checkpoint_digest

            weights_part = f"ckpt{_checkpoint_digest(params, None)}"
        else:
            weights_part = f"seed{seed}"
        super().__init__(
            embed_batch,
            executor=batch_executor(
                max_batch_size=max_batch_size, sizer=_adaptive_sizer
            ),
            deterministic=True,
            cache_strategy=cache_strategy,
            cache_name=f"TpuImageEmbedder:{preset}:{weights_part}",
        )

    def embed_pixels(self, pixels: "np.ndarray") -> list:
        """``[b, H, W, 3]`` uint8 pixels -> per-row embeddings
        (lazy device rows by default, like the text embedder)."""
        import jax.numpy as jnp

        real = pixels.shape[0]
        b = 8
        while b < real:
            b *= 2
        if b != real:
            pad = np.zeros((b - real,) + pixels.shape[1:], pixels.dtype)
            pixels = np.concatenate([pixels, pad])
        vecs_dev = self._jit_forward(jnp.asarray(pixels))
        return _rows_from_device(vecs_dev, real, self.device_resident)

    def embed_images(self, images: list) -> "np.ndarray":
        """PIL images -> ``[n, out_dim]`` numpy (host), for direct use by
        the parsers' vision seam."""
        return np.stack(
            [np.asarray(v, np.float32) for v in self._fn(list(images))]
        )

    def get_embedding_dimension(self) -> int:
        return self.config.out_dim


class _RemoteEmbedder(UDF):
    """Shared shape of OpenAI/LiteLLM/Gemini embedders: an async UDF over an
    injected client (``client(model=..., input=[text]) -> list[float]``)."""

    def __init__(
        self,
        model: str,
        client: Callable[..., Any] | None = None,
        *,
        capacity: int | None = None,
        timeout: float | None = None,
        cache_strategy: CacheStrategy | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        **client_kwargs: Any,
    ) -> None:
        self.model = model
        self.kwargs = client_kwargs
        if client is None:
            raise ValueError(
                f"{type(self).__name__} needs an async `client` callable "
                "(this environment has no network egress); use "
                "xpacks.llm.mocks.fake_embeddings_model for offline runs"
            )

        async def call(text: str) -> Any:
            result = client(model=self.model, input=str(text), **self.kwargs)
            if hasattr(result, "__await__"):
                result = await result
            return np.asarray(result, np.float32)

        super().__init__(
            call,
            executor=async_executor(capacity=capacity, timeout=timeout),
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
            cache_name=f"{type(self).__name__}:{model}",
        )


class OpenAIEmbedder(_RemoteEmbedder):
    """Reference: embedders.py:85."""

    def __init__(self, model: str = "text-embedding-3-small", **kw: Any):
        super().__init__(model, **kw)


class LiteLLMEmbedder(_RemoteEmbedder):
    """Reference: embedders.py:180."""

    def __init__(self, model: str = "", **kw: Any):
        super().__init__(model, **kw)


class GeminiEmbedder(_RemoteEmbedder):
    """Reference: embedders.py:330."""

    def __init__(self, model: str = "models/text-embedding-004", **kw: Any):
        super().__init__(model, **kw)
