"""RAG question answering (reference: xpacks/llm/question_answering.py).

- BaseRAGQuestionAnswerer (:314): retrieve -> prompt -> answer as dataflow.
- AdaptiveRAGQuestionAnswerer (:620): geometric document-count expansion
  (answer_with_geometric_rag_strategy :97) — start with few docs, re-ask
  with geometrically more when the model reports insufficient information;
  implemented, as in the reference, inside the answering UDF so each query
  row drives its own expansion loop.
"""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.internals.expression import apply as pw_apply
from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm import prompts
from pathway_tpu.xpacks.llm.document_store import DocumentStore

NOT_FOUND = "No information found."


class BaseRAGQuestionAnswerer:
    def __init__(
        self,
        llm: Any,
        indexer: DocumentStore,
        *,
        search_topk: int = 6,
        prompt_template: Any = prompts.prompt_qa,
    ) -> None:
        self.llm = llm
        self.indexer = indexer
        self.search_topk = search_topk
        self.prompt_template = prompt_template

    def answer_query(self, query_table: Table) -> Table:
        """``query_table(prompt: str)`` -> ``(result: str, context_docs)``."""
        topk = self.search_topk
        prepped = query_table.select(
            query=query_table.prompt,
            k=pw_apply(lambda _q: topk, query_table.prompt),
        )
        hits = self.indexer.retrieve_query(prepped)
        template = self.prompt_template
        with_prompt = query_table.restrict(hits).select(
            prompt=query_table.prompt,
            docs=hits.result,
            full_prompt=pw_apply(
                lambda q, docs: template(q, [d["text"] for d in docs]),
                query_table.prompt,
                hits.result,
            ),
        )
        return with_prompt.select(
            result=self.llm(with_prompt.full_prompt),
            context_docs=with_prompt.docs,
        )

    # convenience aliases mirroring the reference server surface
    def summarize_query(self, query_table: Table) -> Table:
        texts = query_table.text_list
        return query_table.select(
            result=self.llm(
                pw_apply(lambda ts: prompts.prompt_summarize(ts), texts)
            )
        )


def answer_with_geometric_rag_strategy(
    question: str,
    documents: Sequence[str],
    llm_call: Any,
    n_starting_documents: int = 2,
    factor: int = 2,
    max_iterations: int = 4,
    not_found_response: str = NOT_FOUND,
) -> str:
    """Reference question_answering.py:97: ask with n docs; if the answer is
    'not found', retry with n*factor docs until exhausted."""
    n = n_starting_documents
    for _ in range(max_iterations):
        docs = list(documents[:n])
        answer = str(llm_call(prompts.prompt_qa(question, docs, not_found_response)))
        if not_found_response.lower() not in answer.lower():
            return answer
        if n >= len(documents):
            break
        n *= factor
    return not_found_response


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    def __init__(
        self,
        llm: Any,
        indexer: DocumentStore,
        *,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        search_topk: int = 16,
    ) -> None:
        super().__init__(llm, indexer, search_topk=search_topk)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations

    def answer_query(self, query_table: Table) -> Table:
        topk = self.search_topk
        prepped = query_table.select(
            query=query_table.prompt,
            k=pw_apply(lambda _q: topk, query_table.prompt),
        )
        hits = self.indexer.retrieve_query(prepped)
        llm = self.llm
        n0, factor, iters = (
            self.n_starting_documents,
            self.factor,
            self.max_iterations,
        )

        def adaptive_sync(question: str, docs: tuple) -> str:
            def llm_call(prompt: str) -> str:
                results = llm.execute_rows([(prompt,)])
                ok, value = results[0]
                if not ok:
                    raise value
                return str(value)

            return answer_with_geometric_rag_strategy(
                question,
                [d["text"] for d in docs],
                llm_call,
                n_starting_documents=n0,
                factor=factor,
                max_iterations=iters,
            )

        # async UDF so the expansion loops of all queries in a commit fan
        # out concurrently instead of serializing on the scheduler thread
        # (reference runs these as async coroutines too)
        async def adaptive(question: str, docs: tuple) -> str:
            import asyncio

            return await asyncio.to_thread(adaptive_sync, question, docs)

        from pathway_tpu.internals.udfs import UDF

        adaptive_udf = UDF(adaptive, cache_name=f"AdaptiveRAG:{id(self)}")
        base = query_table.restrict(hits)
        return base.select(
            result=adaptive_udf(query_table.prompt, hits.result),
            context_docs=hits.result,
        )


class SummaryQuestionAnswerer(BaseRAGQuestionAnswerer):
    pass


class RAGClient:
    """HTTP client for the QA REST server (reference :854)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8755) -> None:
        self.base = f"http://{host}:{port}"

    def _post(self, path: str, payload: dict) -> Any:
        import json
        import urllib.request

        req = urllib.request.Request(
            self.base + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())

    def answer(self, prompt: str) -> Any:
        return self._post("/v1/pw_ai_answer", {"prompt": prompt})

    pw_ai_answer = answer
