"""SharePoint file source (reference: xpacks/connectors/sharepoint/, 376
LoC — a licensed connector polling a SharePoint document library).

Entitlement-gated like the reference (license.rs XPACK_SHAREPOINT). The
site is reached through an injected ``client`` with the object-store seam
(``list_objects(prefix) -> [(path, version)]`` / ``get_object(path) ->
bytes``) — an Office365/Graph adapter in deployments, a fake in tests.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.connectors import IdentityParser
from pathway_tpu.engine.storage import ObjectStoreReader
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.license import (
    ENTITLEMENT_XPACK_SHAREPOINT,
    check_entitlements,
)
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table


def read(
    url: str | None = None,
    *,
    root_path: str = "",
    mode: str = "streaming",
    with_metadata: bool = False,
    client: Any = None,
    **kwargs: Any,
) -> Table:
    """Each library file becomes one binary ``data`` row; edits replace,
    deletions retract (the ObjectStore scanner's diffing)."""
    check_entitlements(ENTITLEMENT_XPACK_SHAREPOINT)
    if client is None:
        raise ValueError(
            "pw.xpacks.connectors.sharepoint.read needs an injected client "
            "(list_objects/get_object seam) — no Office365 SDK ships here"
        )
    schema = schema_mod.schema_from_types(data=bytes)
    return input_table(
        schema,
        lambda: ObjectStoreReader(client, root_path, mode=mode, binary=True),
        lambda names: IdentityParser(binary=True),
        source_name=f"sharepoint:{url or root_path}",
        with_metadata=with_metadata,
    )
