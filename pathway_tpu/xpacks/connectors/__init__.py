"""Licensed product connectors (reference: python/pathway/xpacks/connectors/)."""

from pathway_tpu.xpacks.connectors import sharepoint  # noqa: F401
