"""Shared io plumbing (reference: python/pathway/io/_utils.py)."""

from __future__ import annotations

import json
from typing import Any, Callable, Sequence

from pathway_tpu.engine.connectors import InputDriver, Parser, Reader
from pathway_tpu.engine.graph import Scope
from pathway_tpu.engine.value import Json
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table, TableSpec

METADATA_COLUMN = "_metadata"


def converter_for(dtype: dt.DType) -> Callable[[str], Any]:
    base = dtype.strip_optional()
    optional = dtype.is_optional()

    def conv(text: str) -> Any:
        if text == "" and optional:
            return None
        if base == dt.INT:
            return int(text)
        if base == dt.FLOAT:
            return float(text)
        if base == dt.BOOL:
            return text.strip().lower() in ("true", "1", "yes", "on")
        if base == dt.STR:
            return text
        if base == dt.JSON:
            return Json(json.loads(text))
        return text

    return conv


def input_table(
    schema: schema_mod.SchemaMetaclass,
    make_reader: Callable[[], Reader],
    make_parser: Callable[[Sequence[str]], Parser],
    *,
    source_name: str = "input",
    with_metadata: bool = False,
    persistent_id: str | None = None,
    upstream_done: Callable[[], None] | None = None,
    upstream_table: Table | None = None,
    autocommit_duration_ms: int | None = None,
) -> Table:
    """Create a connector-backed table (spec kind "input").

    ``upstream_done`` marks a *loopback* source (AsyncTransformer): its
    reader only closes after the rest of the graph's inputs finish; the run
    loop calls the hook at that point (in build order, so chained loopbacks
    drain upstream-first)."""
    column_names = schema.column_names()
    dtypes = dict(schema.dtypes())
    if with_metadata:
        dtypes[METADATA_COLUMN] = dt.JSON
    all_names = list(dtypes.keys())
    pk = schema.primary_key_columns()
    pk_indices = [column_names.index(p) for p in pk] if pk else None

    def attach(scope: Scope, make_driver: bool = True):
        parser = make_parser(column_names)
        session = scope.input_session(
            len(all_names),
            upsert=getattr(parser, "session_type", "native") == "upsert",
        )
        if not make_driver:
            # replica scopes (sharded workers > 0, follower processes)
            # need the session node for graph alignment but must NOT
            # construct readers: a reader may start threads or consume
            # from external services — only worker 0 reads
            return session, None
        driver = InputDriver(
            session,
            make_reader(),
            parser,
            primary_key_indices=pk_indices,
            source_name=source_name,
            append_metadata=with_metadata,
            autocommit_duration_ms=autocommit_duration_ms,
        )
        if upstream_done is not None:
            driver.upstream_done = upstream_done
            driver.upstream_table = upstream_table
        return session, driver

    return Table(
        TableSpec("input", [], {"attach": attach, "persistent_id": persistent_id}),
        all_names,
        dtypes,
        name=source_name,
    )


def assert_schema_or_value_columns(schema: Any) -> schema_mod.SchemaMetaclass:
    if schema is None:
        raise ValueError("schema= is required for this connector")
    return schema


def attach_writer(
    table: Table, make_writer: Callable[[Sequence[str]], Any]
) -> None:
    """Wire a writer (on_change/on_time_end/on_end) as a sink of ``table``."""
    from pathway_tpu.internals.parse_graph import G

    column_names = table.column_names()

    def attach(scope: Scope, node: Any):
        writer = make_writer(column_names)
        scope.subscribe_table(
            node,
            on_change=writer.on_change,
            on_time_end=writer.on_time_end,
            on_end=writer.on_end,
        )
        return None

    G.add_sink(table, attach)


def post_json(
    url: str,
    payload: dict,
    token: str | None = None,
    timeout: float = 60.0,
    content_type: str = "application/json",
) -> dict:
    """POST a JSON body, parse the JSON response — the shared transport
    behind the REST write connectors (BigQuery insertAll, Pub/Sub
    publish, Kafka schema registry)."""
    import json as _json
    import urllib.request

    headers = {"Content-Type": content_type}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        url, data=_json.dumps(payload).encode(), headers=headers, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return _json.loads(resp.read().decode())


def require(module_names: str, feature: str, injected: Any = None) -> Any:
    """Gate a connector on its client library unless a client is injected."""
    if injected is not None:
        return injected
    import importlib

    try:
        return importlib.import_module(module_names)
    except ImportError as e:
        raise ImportError(
            f"{feature} needs the {module_names!r} client library, which is "
            f"not installed; pass an explicit client/transport object to run "
            f"without it"
        ) from e


def lake_parquet_events(
    path: str,
    column_names: Sequence[str],
    key_indices: Sequence[int] | None,
    lake_kind: str,
):
    """Shared data-lake read leg (Delta + Iceberg): one parquet data file ->
    ParsedEvents. Files written by a pathway writer carry time/diff columns;
    diff=-1 rows become retractions, which need primary-key columns to find
    the row they cancel."""
    import pyarrow.parquet as pq

    from pathway_tpu.engine.connectors import DELETE, INSERT, ParsedEvent

    table = pq.read_table(path)
    data = {c: table.column(c).to_pylist() for c in table.column_names}
    n = table.num_rows
    absent = [None] * n
    events = []
    for i in range(n):
        values = tuple(data.get(name, absent)[i] for name in column_names)
        diff = data["diff"][i] if "diff" in data else 1
        key = (
            tuple(values[j] for j in key_indices) if key_indices else None
        )
        if diff < 0 and key is None:
            raise ValueError(
                f"{lake_kind} table contains retractions (diff=-1); declare "
                "primary_key columns in the read schema so they key the "
                "update stream"
            )
        events.append(
            ParsedEvent(INSERT if diff >= 0 else DELETE, values, key=key)
        )
    return events
