"""Minimal Apache Avro binary codec + object container files.

The Iceberg spec mandates Avro for manifest lists and manifests
(reference: src/connectors/data_lake/iceberg.rs writes them through
iceberg-rust's Avro layer). No Avro library ships in this environment, so
this is a from-scratch implementation of the subset Iceberg metadata
needs — spec: https://avro.apache.org/docs/1.11.1/specification/

Supported schema forms: ``"null" | "boolean" | "int" | "long" | "float" |
"double" | "bytes" | "string"``, records, arrays, maps, fixed, and
unions. The decoder is *generic*: it reads the writer schema embedded in
the container header and decodes against it — the same contract a stock
Avro reader applies, which is what the round-trip tests exercise.

Container layout (spec "Object Container Files"): magic ``Obj\\x01``,
a file-metadata map (``avro.schema`` JSON + ``avro.codec``), a random
16-byte sync marker, then blocks of ``(count, byte-size, data, sync)``.
Only the ``null`` codec is emitted (Iceberg readers must support it).
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, BinaryIO

MAGIC = b"Obj\x01"

# -- primitive binary encoding ------------------------------------------------


def write_long(out: io.BytesIO, n: int) -> None:
    z = (n << 1) ^ (n >> 63)  # arithmetic shift: works for negatives
    z &= (1 << 64) - 1
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def read_long(buf: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # un-zigzag


def write_bytes(out: io.BytesIO, data: bytes) -> None:
    write_long(out, len(data))
    out.write(data)


def read_bytes(buf: BinaryIO) -> bytes:
    n = read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise ValueError("truncated avro bytes")
    return data


# -- schema-driven encode/decode ----------------------------------------------


def encode(out: io.BytesIO, schema: Any, value: Any) -> None:
    if isinstance(schema, str):
        kind = schema
    elif isinstance(schema, list):  # union: branch index then value
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                write_long(out, i)
                encode(out, branch, value)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema}")
    else:
        kind = schema["type"]
    if kind == "null":
        if value is not None:
            raise ValueError(f"non-null {value!r} for null schema")
    elif kind == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif kind in ("int", "long"):
        write_long(out, int(value))
    elif kind == "float":
        out.write(struct.pack("<f", float(value)))
    elif kind == "double":
        out.write(struct.pack("<d", float(value)))
    elif kind == "bytes":
        write_bytes(out, bytes(value))
    elif kind == "string":
        write_bytes(out, str(value).encode())
    elif kind == "fixed":
        data = bytes(value)
        if len(data) != schema["size"]:
            raise ValueError("fixed size mismatch")
        out.write(data)
    elif kind == "record":
        for field in schema["fields"]:
            fv = value.get(field["name"]) if isinstance(value, dict) else None
            if fv is None and "default" in field:
                fv = field["default"]
            encode(out, field["type"], fv)
    elif kind == "array":
        items = list(value or ())
        if items:
            write_long(out, len(items))
            for item in items:
                encode(out, schema["items"], item)
        write_long(out, 0)
    elif kind == "map":
        entries = dict(value or {})
        if entries:
            write_long(out, len(entries))
            for k, v in entries.items():
                write_bytes(out, str(k).encode())
                encode(out, schema["values"], v)
        write_long(out, 0)
    else:
        raise ValueError(f"unsupported avro schema {schema!r}")


def _matches(branch: Any, value: Any) -> bool:
    kind = branch if isinstance(branch, str) else branch["type"]
    if kind == "null":
        return value is None
    if value is None:
        return False
    if kind == "boolean":
        return isinstance(value, bool)
    if kind in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if kind in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if kind == "string":
        return isinstance(value, str)
    if kind in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if kind == "record":
        return isinstance(value, dict)
    if kind == "array":
        return isinstance(value, (list, tuple))
    if kind == "map":
        return isinstance(value, dict)
    return False


def decode(buf: BinaryIO, schema: Any) -> Any:
    if isinstance(schema, str):
        kind = schema
    elif isinstance(schema, list):
        idx = read_long(buf)
        return decode(buf, schema[idx])
    else:
        kind = schema["type"]
    if kind == "null":
        return None
    if kind == "boolean":
        return buf.read(1) == b"\x01"
    if kind in ("int", "long"):
        return read_long(buf)
    if kind == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if kind == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if kind == "bytes":
        return read_bytes(buf)
    if kind == "string":
        return read_bytes(buf).decode()
    if kind == "fixed":
        return buf.read(schema["size"])
    if kind == "record":
        return {
            field["name"]: decode(buf, field["type"])
            for field in schema["fields"]
        }
    if kind == "array":
        out = []
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:  # negative count: byte size follows (skippable form)
                read_long(buf)
                n = -n
            for _ in range(n):
                out.append(decode(buf, schema["items"]))
    if kind == "map":
        out: dict = {}
        while True:
            n = read_long(buf)
            if n == 0:
                return out
            if n < 0:
                read_long(buf)
                n = -n
            for _ in range(n):
                k = read_bytes(buf).decode()
                out[k] = decode(buf, schema["values"])
    raise ValueError(f"unsupported avro schema {schema!r}")


# -- object container files ---------------------------------------------------

_META_SCHEMA = {"type": "map", "values": "bytes"}


def write_container(
    path: str | os.PathLike,
    schema: dict,
    records: list[dict],
    metadata: dict[str, str] | None = None,
) -> None:
    """Write an Avro object container file (null codec, one block)."""
    import secrets

    sync = secrets.token_bytes(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(), "avro.codec": b"null"}
    for k, v in (metadata or {}).items():
        meta[k] = v.encode() if isinstance(v, str) else bytes(v)
    encode(out, _META_SCHEMA, meta)
    out.write(sync)
    block = io.BytesIO()
    for rec in records:
        encode(block, schema, rec)
    data = block.getvalue()
    write_long(out, len(records))
    write_long(out, len(data))
    out.write(data)
    out.write(sync)
    tmp = os.fspath(path) + ".tmp"
    with open(tmp, "wb") as f:
        f.write(out.getvalue())
    os.replace(tmp, path)


def read_container(path: str | os.PathLike) -> tuple[dict, list[dict], dict]:
    """-> (writer schema, records, file metadata). Generic: decodes with
    the schema embedded in the header, like any conforming Avro reader."""
    with open(path, "rb") as f:
        raw = f.read()
    buf = io.BytesIO(raw)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an avro object container file")
    meta = decode(buf, _META_SCHEMA)
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", ""):
        raise ValueError(f"{path}: unsupported avro codec {codec!r}")
    schema = json.loads(meta["avro.schema"].decode())
    sync = buf.read(16)
    records: list[dict] = []
    while buf.tell() < len(raw):
        n = read_long(buf)
        size = read_long(buf)
        block = io.BytesIO(buf.read(size))
        for _ in range(n):
            records.append(decode(block, schema))
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
    return schema, records, {
        k: v.decode(errors="replace") for k, v in meta.items()
    }
