"""pw.io.iceberg — Apache Iceberg table connector
(reference: python/pathway/io/iceberg/__init__.py;
src/connectors/data_lake/iceberg.rs — REST catalog + iceberg-rust).

The reference speaks to a live REST catalog service through iceberg-rust.
Neither a catalog service nor an Avro library exists in this image, so this
is a native implementation of the Iceberg *table layout* over a
hadoop-style filesystem catalog (``warehouse/namespace/table``):

- ``metadata/vN.metadata.json`` — spec-shaped table metadata (format
  version 2 fields: schemas with field-ids, snapshots with sequence
  numbers, current-snapshot-id, snapshot-log), ``version-hint.text``
  pointing at the current version (the hadoop catalog commit protocol:
  write-new-then-atomic-rename).
- snapshots reference a manifest list which references manifests which
  list parquet data files. Manifest lists and manifests are genuine Avro
  object container files carrying the spec's v2 record schemas and
  field-ids (io/_avro.py implements the codec from scratch — no Avro
  library ships here); a ``.json`` debug twin sits beside each Avro file
  for transparent inspection. Pre-Avro tables (``.json`` manifests) are
  still readable.
- data files are genuine parquet (pyarrow), with ``time``/``diff``
  columns so the update stream round-trips (retractions re-emerge as
  deletions on read, matching the Delta connector's convention).

The streaming reader polls ``version-hint.text`` and emits rows of data
files added by unseen snapshots; ``mode="static"`` reads the current
snapshot once. Offsets persist via ``state()``/``restore_state``.
"""

from __future__ import annotations

import json
import os
import time as _time
import uuid
from typing import Any, Sequence

from pathway_tpu.engine.connectors import Reader
from pathway_tpu.engine.value import Json, Pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, input_table

_METADATA = "metadata"
_DATA = "data"
_VERSION_HINT = "version-hint.text"


def _iceberg_type(dtype: dt.DType) -> str:
    base = dtype.strip_optional()
    if base == dt.INT:
        return "long"
    if base == dt.FLOAT:
        return "double"
    if base == dt.BOOL:
        return "boolean"
    if base == dt.BYTES:
        return "binary"
    return "string"


def _schema_json(column_names: Sequence[str], dtypes: dict) -> dict:
    fields = [
        {
            "id": i + 1,
            "name": name,
            "required": False,
            "type": _iceberg_type(dtypes.get(name, dt.STR)),
        }
        for i, name in enumerate(column_names)
    ]
    return {"type": "struct", "schema-id": 0, "fields": fields}


def _is_rest_uri(catalog_uri: str | os.PathLike) -> bool:
    uri = os.fspath(catalog_uri)
    return isinstance(uri, str) and uri.split("://", 1)[0] in (
        "http",
        "https",
    )


def _check_local(catalog_uri: str | os.PathLike) -> str:
    uri = os.fspath(catalog_uri)
    if isinstance(uri, str) and "://" in uri:
        scheme = uri.split("://", 1)[0]
        if scheme != "file":
            # http(s) goes through the REST catalog path; other object-
            # store warehouses (s3/gs/abfs/...) need services this build
            # cannot reach — refuse rather than silently writing to a
            # local dir named "s3:"
            raise NotImplementedError(
                f"pw.io.iceberg speaks the filesystem (hadoop-style) "
                f"catalog or an http(s) REST catalog; {scheme}:// "
                f"locations are unreachable from this build"
            )
        uri = uri[len("file://"):]
    return uri


def table_location(
    catalog_uri: str | os.PathLike,
    namespace: Sequence[str],
    table_name: str,
) -> str:
    """warehouse root + namespace path + table name -> table directory."""
    return os.path.join(_check_local(catalog_uri), *namespace, table_name)


def _metadata_path(loc: str, version: int) -> str:
    return os.path.join(loc, _METADATA, f"v{version}.metadata.json")


def _current_version(loc: str) -> int | None:
    hint = os.path.join(loc, _METADATA, _VERSION_HINT)
    try:
        with open(hint, encoding="utf-8") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _read_metadata(loc: str, version: int) -> dict:
    with open(_metadata_path(loc, version), encoding="utf-8") as f:
        return json.load(f)


def _atomic_write(path: str, payload: str, exclusive: bool = False) -> None:
    """Write-new-then-rename. ``exclusive=True`` is the hadoop catalog
    commit: publishing an existing version must FAIL (hard-link then
    unlink raises FileExistsError) so concurrent writers can't silently
    clobber each other's snapshots."""
    tmp = path + f".tmp-{uuid.uuid4()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(payload)
    if exclusive:
        try:
            os.link(tmp, path)
        finally:
            os.unlink(tmp)
    else:
        os.replace(tmp, path)


# -- Avro manifests (Iceberg spec, format v2) --------------------------------
#
# Manifest lists and manifests are Avro object container files with the
# spec's field-ids, written via the from-scratch codec in io/_avro.py
# (no Avro library in this environment). A ``.json`` debug twin is kept
# beside each for transparency.

_FIELD_SUMMARY_SCHEMA = {
    "type": "record",
    "name": "r508",
    "fields": [
        {"name": "contains_null", "type": "boolean", "field-id": 509},
        {
            "name": "contains_nan",
            "type": ["null", "boolean"],
            "field-id": 518,
        },
        {"name": "lower_bound", "type": ["null", "bytes"], "field-id": 510},
        {"name": "upper_bound", "type": ["null", "bytes"], "field-id": 511},
    ],
}

MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
        {
            "name": "partitions",
            "type": ["null", {"type": "array", "items": _FIELD_SUMMARY_SCHEMA}],
            "field-id": 507,
        },
    ],
}

MANIFEST_ENTRY_SCHEMA = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"], "field-id": 1},
        {
            "name": "sequence_number",
            "type": ["null", "long"],
            "field-id": 3,
        },
        {
            "name": "file_sequence_number",
            "type": ["null", "long"],
            "field-id": 4,
        },
        {
            "name": "data_file",
            "field-id": 2,
            "type": {
                "type": "record",
                "name": "r2",
                "fields": [
                    {"name": "content", "type": "int", "field-id": 134},
                    {"name": "file_path", "type": "string", "field-id": 100},
                    {
                        "name": "file_format",
                        "type": "string",
                        "field-id": 101,
                    },
                    {
                        "name": "partition",
                        "field-id": 102,
                        "type": {
                            "type": "record",
                            "name": "r102",
                            "fields": [],  # unpartitioned spec
                        },
                    },
                    {"name": "record_count", "type": "long", "field-id": 103},
                    {
                        "name": "file_size_in_bytes",
                        "type": "long",
                        "field-id": 104,
                    },
                ],
            },
        },
    ],
}


def _write_manifest(
    path: str, entries: list[dict], table_schema: dict
) -> None:
    from pathway_tpu.io import _avro

    _avro.write_container(
        path,
        MANIFEST_ENTRY_SCHEMA,
        entries,
        metadata={
            "schema": json.dumps(table_schema),
            "schema-id": "0",
            "partition-spec": "[]",
            "partition-spec-id": "0",
            "format-version": "2",
            "content": "data",
        },
    )
    _atomic_write(path + ".json", json.dumps({"entries": entries}, indent=1))


def _write_manifest_list(
    path: str, manifests: list[dict], *, snapshot_id: int, sequence_number: int
) -> None:
    from pathway_tpu.io import _avro

    _avro.write_container(
        path,
        MANIFEST_FILE_SCHEMA,
        manifests,
        metadata={
            "snapshot-id": str(snapshot_id),
            "sequence-number": str(sequence_number),
            "format-version": "2",
        },
    )
    _atomic_write(
        path + ".json", json.dumps({"manifests": manifests}, indent=1)
    )


def _read_manifest_list(path: str) -> list[dict]:
    """Avro manifest list -> entries; pre-Avro (JSON) tables still read
    AND append: legacy entries are normalized to the full v2 field set so
    carrying them into the next snapshot's Avro list encodes cleanly."""
    if path.endswith(".json"):
        with open(path, encoding="utf-8") as f:
            entries = json.load(f)["manifests"]
        base = os.path.dirname(os.path.dirname(path))  # table location
        out = []
        for e in entries:
            mpath = os.path.join(base, e["manifest_path"])
            seq = e.get("sequence_number", 0)
            out.append(
                {
                    "manifest_path": e["manifest_path"],
                    "manifest_length": e.get(
                        "manifest_length",
                        os.path.getsize(mpath) if os.path.exists(mpath) else 0,
                    ),
                    "partition_spec_id": e.get("partition_spec_id", 0),
                    "content": e.get("content", 0),
                    "sequence_number": seq,
                    "min_sequence_number": e.get("min_sequence_number", seq),
                    "added_snapshot_id": e["added_snapshot_id"],
                    "added_files_count": e.get("added_files_count", 0),
                    "existing_files_count": e.get("existing_files_count", 0),
                    "deleted_files_count": e.get("deleted_files_count", 0),
                    "added_rows_count": e.get("added_rows_count", 0),
                    "existing_rows_count": e.get("existing_rows_count", 0),
                    "deleted_rows_count": e.get("deleted_rows_count", 0),
                    "partitions": e.get("partitions", []),
                }
            )
        return out
    from pathway_tpu.io import _avro

    _schema, records, _meta = _avro.read_container(path)
    return records


def _read_manifest(path: str) -> list[dict]:
    if path.endswith(".json"):
        with open(path, encoding="utf-8") as f:
            return json.load(f)["entries"]
    from pathway_tpu.io import _avro

    _schema, records, _meta = _avro.read_container(path)
    return records


class FilesystemCatalog:
    """Hadoop-style catalog: the table's metadata directory IS the
    catalog; commits publish vN+1 with an exclusive create."""

    def __init__(self, location: str) -> None:
        self.location = os.fspath(location)

    def ensure(self, column_names: Sequence[str], dtypes: dict) -> str:
        os.makedirs(os.path.join(self.location, _METADATA), exist_ok=True)
        os.makedirs(os.path.join(self.location, _DATA), exist_ok=True)
        if _current_version(self.location) is None:
            metadata = {
                "format-version": 2,
                "table-uuid": str(uuid.uuid4()),
                "location": self.location,
                "last-sequence-number": 0,
                "last-updated-ms": int(_time.time() * 1000),
                "last-column-id": len(column_names) + 2,
                "current-schema-id": 0,
                "schemas": [
                    _schema_json(
                        list(column_names) + ["time", "diff"],
                        {**dtypes, "time": dt.INT, "diff": dt.INT},
                    )
                ],
                "default-spec-id": 0,
                "partition-specs": [{"spec-id": 0, "fields": []}],
                "last-partition-id": 999,
                "default-sort-order-id": 0,
                "sort-orders": [{"order-id": 0, "fields": []}],
                "properties": {},
                "current-snapshot-id": -1,
                "snapshots": [],
                "snapshot-log": [],
                "metadata-log": [],
            }
            self.commit(0, metadata)
        return self.location

    def load(self) -> tuple[Any, dict | None]:
        version = _current_version(self.location)
        if version is None:
            return None, None
        return version, _read_metadata(self.location, version)

    def commit(self, token: Any, metadata: dict) -> None:
        version = int(token) + 1
        _atomic_write(
            _metadata_path(self.location, version),
            json.dumps(metadata, indent=1),
            exclusive=True,  # lose the race -> raise, never clobber
        )
        _atomic_write(
            os.path.join(self.location, _METADATA, _VERSION_HINT),
            str(version),
        )


class RestCatalog:
    """REST catalog (reference: src/connectors/data_lake/iceberg.rs):
    metadata lives in the catalog service, reached through
    io/_iceberg_rest.py's client; data/manifest files live at the
    table's ``location``. Commits send the spec's CommitTableRequest
    (assert-table-uuid + assert-ref-snapshot-id requirements,
    add-snapshot + set-snapshot-ref updates) — a stale snapshot gets
    409 and the engine retries the batch, mirroring the filesystem
    catalog's exclusive-create race."""

    def __init__(
        self,
        uri: str,
        namespace: Sequence[str],
        table_name: str,
        *,
        token: str | None = None,
    ) -> None:
        from pathway_tpu.io._iceberg_rest import RestCatalogClient

        self.client = RestCatalogClient(uri, token=token)
        self.namespace = list(namespace)
        self.table_name = table_name
        self.location: str | None = None

    def ensure(self, column_names: Sequence[str], dtypes: dict) -> str:
        from pathway_tpu.io._iceberg_rest import IcebergRestError

        loaded = self.client.load_table(self.namespace, self.table_name)
        if loaded is None:
            self.client.create_namespace(self.namespace)
            try:
                loaded = self.client.create_table(
                    self.namespace,
                    self.table_name,
                    _schema_json(
                        list(column_names) + ["time", "diff"],
                        {**dtypes, "time": dt.INT, "diff": dt.INT},
                    ),
                )
            except IcebergRestError as exc:
                if exc.code != 409:
                    raise
                # lost the create race: the table exists now — use it
                loaded = self.client.load_table(
                    self.namespace, self.table_name
                )
                if loaded is None:
                    raise
        self.location = loaded["metadata"]["location"]
        return self.location

    def load(self) -> tuple[Any, dict | None]:
        loaded = self.client.load_table(self.namespace, self.table_name)
        if loaded is None:
            return None, None
        meta = loaded["metadata"]
        self.location = meta["location"]
        head = meta.get("refs", {}).get("main", {}).get("snapshot-id")
        return (meta["table-uuid"], head), meta

    def commit(self, token: Any, metadata: dict) -> None:
        table_uuid, head = token
        snapshot = metadata["snapshots"][-1]
        self.client.commit_table(
            self.namespace,
            self.table_name,
            requirements=[
                {"type": "assert-table-uuid", "uuid": table_uuid},
                {
                    "type": "assert-ref-snapshot-id",
                    "ref": "main",
                    "snapshot-id": head,
                },
            ],
            updates=[
                {"action": "add-snapshot", "snapshot": snapshot},
                {
                    "action": "set-snapshot-ref",
                    "ref-name": "main",
                    "type": "branch",
                    "snapshot-id": snapshot["snapshot-id"],
                },
            ],
        )


class IcebergWriter:
    """Append-only Iceberg writer: one parquet data file + one snapshot
    commit per engine commit (reference data_lake/writer.rs batching).
    The catalog seam carries the commit protocol: filesystem
    (version-hint exclusive create) or REST (CommitTableRequest)."""

    def __init__(
        self,
        location: str | None,
        column_names: Sequence[str],
        dtypes: dict,
        catalog: Any = None,
    ):
        self.catalog = (
            catalog
            if catalog is not None
            else FilesystemCatalog(os.fspath(location))
        )
        self.column_names = list(column_names)
        self.dtypes = dtypes
        self._rows: list[tuple] = []
        self.location = self.catalog.ensure(self.column_names, dtypes)

    def on_change(
        self, key: Pointer, values: tuple, time: int, diff: int
    ) -> None:
        row = tuple(
            json.dumps(v.value) if isinstance(v, Json) else v for v in values
        )
        self._rows.append(row + (time, diff))

    def on_time_end(self, time: int) -> None:
        if not self._rows:
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        names = self.column_names + ["time", "diff"]
        columns = list(zip(*self._rows))
        arrow = pa.table({n: list(c) for n, c in zip(names, columns)})
        n_rows = len(self._rows)
        fname = f"{uuid.uuid4()}.parquet"
        fpath = os.path.join(self.location, _DATA, fname)
        pq.write_table(arrow, fpath)

        token, metadata = self.catalog.load()
        if metadata is None:
            raise RuntimeError(
                f"iceberg table at {self.location}: the catalog no longer "
                f"knows the table; it was deleted or corrupted after this "
                f"writer opened it"
            )
        seq = metadata["last-sequence-number"] + 1
        snapshot_id = int(uuid.uuid4().int % (1 << 62))
        now_ms = int(_time.time() * 1000)

        manifest_name = f"manifest-{uuid.uuid4()}.avro"
        manifest_path = os.path.join(self.location, _METADATA, manifest_name)
        entry = {
            "status": 1,  # ADDED
            "snapshot_id": snapshot_id,
            "sequence_number": seq,
            "file_sequence_number": seq,
            "data_file": {
                "content": 0,
                "file_path": os.path.join(_DATA, fname),
                "file_format": "PARQUET",
                "partition": {},
                "record_count": n_rows,
                "file_size_in_bytes": os.path.getsize(fpath),
            },
        }
        _write_manifest(
            manifest_path,
            [entry],
            table_schema=metadata["schemas"][0],
        )
        # new manifest list = previous snapshot's list + this manifest
        manifests: list[dict] = []
        current = metadata.get("current-snapshot-id", -1)
        for snap in metadata["snapshots"]:
            if snap["snapshot-id"] == current:
                manifests = _read_manifest_list(
                    os.path.join(self.location, snap["manifest-list"])
                )
        manifests = manifests + [
            {
                "manifest_path": os.path.join(_METADATA, manifest_name),
                "manifest_length": os.path.getsize(manifest_path),
                "partition_spec_id": 0,
                "content": 0,  # data
                "sequence_number": seq,
                "min_sequence_number": seq,
                "added_snapshot_id": snapshot_id,
                "added_files_count": 1,
                "existing_files_count": 0,
                "deleted_files_count": 0,
                "added_rows_count": n_rows,
                "existing_rows_count": 0,
                "deleted_rows_count": 0,
                "partitions": [],
            }
        ]
        list_name = f"snap-{snapshot_id}-{uuid.uuid4()}.avro"
        _write_manifest_list(
            os.path.join(self.location, _METADATA, list_name),
            manifests,
            snapshot_id=snapshot_id,
            sequence_number=seq,
        )
        metadata["last-sequence-number"] = seq
        metadata["last-updated-ms"] = now_ms
        metadata["current-snapshot-id"] = snapshot_id
        metadata["snapshots"].append(
            {
                "snapshot-id": snapshot_id,
                "sequence-number": seq,
                "timestamp-ms": now_ms,
                "manifest-list": os.path.join(_METADATA, list_name),
                "summary": {
                    "operation": "append",
                    "added-data-files": "1",
                    "added-records": str(n_rows),
                },
                "schema-id": 0,
            }
        )
        metadata["snapshot-log"].append(
            {"snapshot-id": snapshot_id, "timestamp-ms": now_ms}
        )
        if isinstance(token, int):  # fs catalog: token is the version
            metadata["metadata-log"].append(
                {
                    "metadata-file": _metadata_path(self.location, token),
                    "timestamp-ms": now_ms,
                }
            )
        self.catalog.commit(token, metadata)
        # only a fully committed snapshot releases the buffer: if the
        # parquet write or the exclusive version commit raised (lost
        # catalog race), the rows stay queued for the next flush — an
        # orphaned unreferenced data file is harmless, lost rows are not
        self._rows = []

    def on_end(self) -> None:
        self.on_time_end(-1)


class IcebergReader(Reader):
    """Poll the catalog's version hint; emit rows of data files added by
    unseen snapshots (in sequence-number order). Rows written by a pathway
    writer carry time/diff columns — diff=-1 rows become retractions."""

    def __init__(
        self,
        location: str | None,
        column_names: Sequence[str],
        mode: str,
        key_indices: Sequence[int] | None = None,
        catalog: Any = None,
    ):
        self.catalog = (
            catalog
            if catalog is not None
            else FilesystemCatalog(os.fspath(location))
        )
        self.location = (
            os.fspath(location) if location is not None else None
        )
        self.column_names = list(column_names)
        self.mode = mode
        self.key_indices = list(key_indices) if key_indices else None
        #: snapshots up to this sequence number were already emitted
        #: (sequence numbers are strictly increasing, so the offset is
        #: O(1) like DeltaReader's next_version)
        self._seen_seq = 0
        self._done_static = False

    def _events_of_file(self, rel_path: str):
        from pathway_tpu.io._utils import lake_parquet_events

        return lake_parquet_events(
            os.path.join(self.location, rel_path),
            self.column_names,
            self.key_indices,
            "iceberg",
        )

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        if self._done_static:
            return [], True
        entries = []
        _token, metadata = self.catalog.load()
        if metadata is not None:
            # REST tables learn their file location from the catalog
            self.location = metadata.get("location", self.location)
            fresh = sorted(
                (
                    s
                    for s in metadata["snapshots"]
                    if s["sequence-number"] > self._seen_seq
                ),
                key=lambda s: s["sequence-number"],
            )
            for snap in fresh:
                manifests = _read_manifest_list(
                    os.path.join(self.location, snap["manifest-list"])
                )
                for m in manifests:
                    if m["added_snapshot_id"] != snap["snapshot-id"]:
                        continue  # carried over from an earlier snapshot
                    for entry in _read_manifest(
                        os.path.join(self.location, m["manifest_path"])
                    ):
                        if entry["status"] != 1:  # ADDED files only
                            continue
                        path = entry["data_file"]["file_path"]
                        entries.append(
                            (
                                self._events_of_file(path),
                                f"iceberg:{path}",
                                {"path": path},
                            )
                        )
                self._seen_seq = snap["sequence-number"]
        if self.mode == "static":
            self._done_static = True
        return entries, self.mode == "static"

    def state(self) -> dict:
        return {"seen_seq": self._seen_seq}

    def restore_state(self, state: dict) -> None:
        self._seen_seq = int(state.get("seen_seq", 0))
        self._done_static = False


def _rest_catalog_factory(
    catalog_uri: str | os.PathLike,
    namespace: Sequence[str] | None,
    table_name: str | None,
    kwargs: dict,
):
    """Shared REST dispatch for read()/write(): validation + a factory
    producing fresh RestCatalog clients."""
    if namespace is None or table_name is None:
        raise ValueError(
            "pw.io.iceberg: REST catalogs need namespace and table_name"
        )
    uri = os.fspath(catalog_uri)
    token = kwargs.get("credentials")
    return lambda: RestCatalog(uri, namespace, table_name, token=token)


def read(
    catalog_uri: str | os.PathLike,
    namespace: Sequence[str] | None = None,
    table_name: str | None = None,
    schema: schema_mod.SchemaMetaclass | None = None,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read an Iceberg table. An http(s) ``catalog_uri`` speaks the REST
    catalog protocol (reference src/connectors/data_lake/iceberg.rs);
    otherwise it is the warehouse root of the filesystem catalog.
    ``namespace`` + ``table_name`` locate the table under it — both may be
    omitted when ``catalog_uri`` IS the table directory (filesystem
    only)."""
    if schema is None:
        raise ValueError("schema= is required for pw.io.iceberg.read")
    if (namespace is None) != (table_name is None):
        raise ValueError(
            "pw.io.iceberg: pass both namespace and table_name (table under "
            "the warehouse root), or neither (catalog_uri IS the table dir)"
        )
    from pathway_tpu.engine.storage import TransparentParser

    column_names = schema.column_names()
    pk = schema.primary_key_columns()
    key_indices = [column_names.index(p) for p in pk] if pk else None
    if _is_rest_uri(catalog_uri):
        make_catalog = _rest_catalog_factory(
            catalog_uri, namespace, table_name, kwargs
        )

        def make_rest_reader():
            return IcebergReader(
                None, column_names, mode, key_indices,
                catalog=make_catalog(),
            )

        return input_table(
            schema,
            make_rest_reader,
            lambda names: TransparentParser(names),
            source_name=(
                f"iceberg:{os.fspath(catalog_uri)}/"
                f"{'.'.join(namespace)}/{table_name}"
            ),
            persistent_id=persistent_id,
            autocommit_duration_ms=autocommit_duration_ms,
        )
    loc = (
        table_location(catalog_uri, namespace, table_name)
        if namespace is not None and table_name is not None
        else _check_local(catalog_uri)
    )
    return input_table(
        schema,
        lambda: IcebergReader(loc, column_names, mode, key_indices),
        lambda names: TransparentParser(names),
        source_name=f"iceberg:{loc}",
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def write(
    table: Table,
    catalog_uri: str | os.PathLike,
    namespace: Sequence[str] | None = None,
    table_name: str | None = None,
    *,
    min_commit_frequency: int | None = None,
    **kwargs: Any,
) -> None:
    """Write a table's update stream as Iceberg snapshot appends. An
    http(s) ``catalog_uri`` speaks the REST catalog protocol (reference
    src/connectors/data_lake/iceberg.rs); otherwise the filesystem
    (hadoop-style) catalog remains the default."""
    if (namespace is None) != (table_name is None):
        raise ValueError(
            "pw.io.iceberg: pass both namespace and table_name (table under "
            "the warehouse root), or neither (catalog_uri IS the table dir)"
        )
    dtypes = dict(table._dtypes)
    if _is_rest_uri(catalog_uri):
        make_catalog = _rest_catalog_factory(
            catalog_uri, namespace, table_name, kwargs
        )

        def make_rest_writer(column_names):
            return IcebergWriter(
                None, column_names, dtypes, catalog=make_catalog()
            )

        attach_writer(table, make_rest_writer)
        return
    loc = (
        table_location(catalog_uri, namespace, table_name)
        if namespace is not None and table_name is not None
        else _check_local(catalog_uri)
    )

    def make_writer(column_names):
        return IcebergWriter(loc, column_names, dtypes)

    attach_writer(table, make_writer)
