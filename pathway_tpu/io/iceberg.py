"""pw.io.iceberg — Apache Iceberg connector (reference:
python/pathway/io/iceberg/__init__.py; src/connectors/data_lake/iceberg.rs
— REST catalog + iceberg-rust). Requires a live REST catalog service, which
this image cannot reach; the API surface is kept and gated. Local lakehouse
workflows are served by pw.io.deltalake, which is fully implemented."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import require


def read(
    catalog_uri: str,
    namespace: list[str],
    table_name: str,
    schema: Any = None,
    *,
    mode: str = "streaming",
    **kwargs: Any,
) -> Table:
    require("pyiceberg", "pw.io.iceberg")
    raise NotImplementedError("iceberg needs a reachable REST catalog")


def write(
    table: Table,
    catalog_uri: str,
    namespace: list[str],
    table_name: str,
    **kwargs: Any,
) -> None:
    require("pyiceberg", "pw.io.iceberg")
    raise NotImplementedError("iceberg needs a reachable REST catalog")
