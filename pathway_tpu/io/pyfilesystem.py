"""pw.io.pyfilesystem — read any PyFilesystem2 filesystem (reference:
python/pathway/io/pyfilesystem/__init__.py). Accepts any object with the
PyFilesystem ``walk.files()`` / ``readbytes`` / ``getinfo`` surface — an
``fs.open_fs(...)`` result, or a compatible fake in tests."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.connectors import IdentityParser
from pathway_tpu.engine.storage import ObjectStoreReader
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table


class _FsStore:
    def __init__(self, source: Any, path: str) -> None:
        self.source = source
        self.path = path

    def list_objects(self, prefix: str):
        out = []
        for fpath in self.source.walk.files(self.path or "/"):
            info = self.source.getinfo(fpath, namespaces=["details"])
            sig = f"{getattr(info, 'size', 0)}:{getattr(info, 'modified', '')}"
            out.append((fpath, sig))
        return out

    def get_object(self, key: str) -> bytes:
        return self.source.readbytes(key)


def read(
    source: Any,
    path: str = "",
    *,
    mode: str = "streaming",
    format: str = "binary",  # noqa: A002
    with_metadata: bool = False,
    **kwargs: Any,
) -> Table:
    schema = schema_mod.schema_from_types(
        data=bytes if format == "binary" else str
    )
    store = _FsStore(source, path)
    return input_table(
        schema,
        lambda: ObjectStoreReader(
            store, "", mode=mode, binary=format == "binary"
        ),
        lambda names: IdentityParser(binary=format == "binary"),
        source_name=f"pyfilesystem:{path}",
        with_metadata=with_metadata,
    )
