"""Minimal Kafka binary wire protocol: client + in-process broker.

The reference reaches Kafka through librdkafka
(src/connectors/data_storage.rs:673); this image has no Kafka client
library, so the framework speaks the wire protocol itself. Implemented
(non-flexible request versions, fixed headers):

- ApiVersions v0, Metadata v1, Produce v3, Fetch v4, ListOffsets v1
- RecordBatch v2 (magic 2) encoding/decoding: zigzag varints, CRC32C
  over the post-crc section, record headers

:class:`KafkaWireClient` is the client; :class:`FakeKafkaBroker` is an
in-process TCP broker speaking the same frames (single partition per
topic) used by the round-trip tests and offline demos — the bytes on the
socket are genuine Kafka protocol, not an injectable seam.
"""

from __future__ import annotations

import io
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any

# -- CRC32C (Castagnoli), table-driven ---------------------------------------

_CRC32C_POLY = 0x82F63B78


def _make_crc32c_table() -> list[int]:
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- varints ------------------------------------------------------------------


def write_uvarint(out: io.BytesIO, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def write_varint(out: io.BytesIO, n: int) -> None:
    write_uvarint(out, (n << 1) ^ (n >> 63) if n < 0 else n << 1)


def read_uvarint(buf: io.BytesIO) -> int:
    shift = 0
    result = 0
    while True:
        (b,) = buf.read(1)
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result
        shift += 7


def read_varint(buf: io.BytesIO) -> int:
    n = read_uvarint(buf)
    return (n >> 1) ^ -(n & 1)


# -- primitive codecs ---------------------------------------------------------


def _w(out: io.BytesIO, fmt: str, *vals: Any) -> None:
    out.write(struct.pack(">" + fmt, *vals))


def _r(buf: io.BytesIO, fmt: str):
    size = struct.calcsize(">" + fmt)
    vals = struct.unpack(">" + fmt, buf.read(size))
    return vals[0] if len(vals) == 1 else vals


def _w_string(out: io.BytesIO, s: str | None) -> None:
    if s is None:
        _w(out, "h", -1)
    else:
        b = s.encode()
        _w(out, "h", len(b))
        out.write(b)


def _r_string(buf: io.BytesIO) -> str | None:
    n = _r(buf, "h")
    if n < 0:
        return None
    return buf.read(n).decode()


def _w_bytes(out: io.BytesIO, b: bytes | None) -> None:
    if b is None:
        _w(out, "i", -1)
    else:
        _w(out, "i", len(b))
        out.write(b)


def _r_bytes(buf: io.BytesIO) -> bytes | None:
    n = _r(buf, "i")
    if n < 0:
        return None
    return buf.read(n)


# -- RecordBatch v2 -----------------------------------------------------------


@dataclass
class WireRecord:
    value: bytes | None
    key: bytes | None = None
    timestamp: int = 0
    headers: list[tuple[str, bytes]] = field(default_factory=list)
    offset: int = 0  # absolute, filled by decode


def encode_record_batch(records: list[WireRecord], base_offset: int) -> bytes:
    """RecordBatch (magic 2, uncompressed)."""
    first_ts = records[0].timestamp if records else 0
    max_ts = max((r.timestamp for r in records), default=0)
    body = io.BytesIO()
    _w(body, "h", 0)  # attributes: no compression
    _w(body, "i", len(records) - 1)  # last_offset_delta
    _w(body, "qq", first_ts, max_ts)
    _w(body, "qhi", -1, -1, -1)  # producer id/epoch, base sequence
    _w(body, "i", len(records))
    for i, rec in enumerate(records):
        r = io.BytesIO()
        r.write(b"\x00")  # record attributes
        write_varint(r, rec.timestamp - first_ts)
        write_varint(r, i)  # offset delta
        for blob in (rec.key, rec.value):
            if blob is None:
                write_varint(r, -1)
            else:
                write_varint(r, len(blob))
                r.write(blob)
        write_varint(r, len(rec.headers))
        for hk, hv in rec.headers:
            kb = hk.encode()
            write_varint(r, len(kb))
            r.write(kb)
            write_varint(r, len(hv))
            r.write(hv)
        rb = r.getvalue()
        write_varint(body, len(rb))
        body.write(rb)
    payload = body.getvalue()
    crc = crc32c(payload)
    inner = io.BytesIO()
    _w(inner, "i", 0)  # partition leader epoch
    _w(inner, "b", 2)  # magic
    _w(inner, "I", crc)
    inner.write(payload)
    inner_b = inner.getvalue()
    out = io.BytesIO()
    _w(out, "q", base_offset)
    _w(out, "i", len(inner_b))
    out.write(inner_b)
    return out.getvalue()


def decode_record_batches(data: bytes) -> list[WireRecord]:
    """All records of all batches in a fetched record set."""
    buf = io.BytesIO(data)
    out: list[WireRecord] = []
    while True:
        head = buf.read(12)
        if len(head) < 12:
            return out
        base_offset, length = struct.unpack(">qi", head)
        inner = io.BytesIO(buf.read(length))
        _r(inner, "i")  # leader epoch
        magic = _r(inner, "b")
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc = _r(inner, "I")
        payload = inner.read()
        if crc32c(payload) != crc:
            raise ValueError("record batch CRC32C mismatch")
        body = io.BytesIO(payload)
        _r(body, "h")  # attributes
        _r(body, "i")  # last offset delta
        first_ts, _max_ts = _r(body, "qq")
        _r(body, "qhi")
        n = _r(body, "i")
        for _ in range(n):
            rlen = read_varint(body)
            r = io.BytesIO(body.read(rlen))
            r.read(1)  # attributes
            ts_delta = read_varint(r)
            off_delta = read_varint(r)
            klen = read_varint(r)
            key = r.read(klen) if klen >= 0 else None
            vlen = read_varint(r)
            value = r.read(vlen) if vlen >= 0 else None
            headers = []
            for _h in range(read_varint(r)):
                hklen = read_varint(r)
                hk = r.read(hklen).decode()
                hvlen = read_varint(r)
                hv = r.read(hvlen) if hvlen >= 0 else b""
                headers.append((hk, hv))
            out.append(
                WireRecord(
                    value=value,
                    key=key,
                    timestamp=first_ts + ts_delta,
                    headers=headers,
                    offset=base_offset + off_delta,
                )
            )


# -- framing ------------------------------------------------------------------

API_VERSIONS = 18
METADATA = 3
PRODUCE = 0
FETCH = 1
LIST_OFFSETS = 2


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        got = sock.recv(n)
        if not got:
            raise ConnectionError("kafka connection closed")
        chunks.append(got)
        n -= len(got)
    return b"".join(chunks)


class KafkaWireClient:
    """Blocking single-connection Kafka protocol client."""

    def __init__(
        self, host: str, port: int, client_id: str = "pathway-tpu"
    ) -> None:
        self.sock = socket.create_connection((host, port), timeout=30)
        self.client_id = client_id
        self._corr = 0
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _call(self, api_key: int, api_version: int, body: bytes) -> io.BytesIO:
        with self._lock:
            self._corr += 1
            corr = self._corr
            head = io.BytesIO()
            _w(head, "hhi", api_key, api_version, corr)
            _w_string(head, self.client_id)
            frame = head.getvalue() + body
            # pwc-ok: PWC403 — the lock serializes request/response pairs
            self.sock.sendall(struct.pack(">i", len(frame)) + frame)
            (length,) = struct.unpack(">i", _recv_exact(self.sock, 4))
            resp = io.BytesIO(_recv_exact(self.sock, length))
            got_corr = _r(resp, "i")
            if got_corr != corr:
                raise ValueError(
                    f"correlation id mismatch: {got_corr} != {corr}"
                )
            return resp

    def api_versions(self) -> dict[int, tuple[int, int]]:
        resp = self._call(API_VERSIONS, 0, b"")
        error = _r(resp, "h")
        if error:
            raise ValueError(f"ApiVersions error {error}")
        out = {}
        for _ in range(_r(resp, "i")):
            key, lo, hi = _r(resp, "hhh")
            out[key] = (lo, hi)
        return out

    def metadata(self, topics: list[str] | None = None) -> dict:
        body = io.BytesIO()
        if topics is None:
            _w(body, "i", -1)
        else:
            _w(body, "i", len(topics))
            for t in topics:
                _w_string(body, t)
        resp = self._call(METADATA, 1, body.getvalue())
        brokers = []
        for _ in range(_r(resp, "i")):
            node = _r(resp, "i")
            host = _r_string(resp)
            port = _r(resp, "i")
            _r_string(resp)  # rack
            brokers.append({"node_id": node, "host": host, "port": port})
        controller = _r(resp, "i")
        topics_out = {}
        for _ in range(_r(resp, "i")):
            terr = _r(resp, "h")
            name = _r_string(resp)
            _r(resp, "?")  # is_internal
            parts = []
            for _p in range(_r(resp, "i")):
                perr = _r(resp, "h")
                pid = _r(resp, "i")
                leader = _r(resp, "i")
                replicas = [_r(resp, "i") for _x in range(_r(resp, "i"))]
                isr = [_r(resp, "i") for _x in range(_r(resp, "i"))]
                parts.append(
                    {
                        "error": perr,
                        "partition": pid,
                        "leader": leader,
                        "replicas": replicas,
                        "isr": isr,
                    }
                )
            topics_out[name] = {"error": terr, "partitions": parts}
        return {
            "brokers": brokers,
            "controller": controller,
            "topics": topics_out,
        }

    def produce(
        self,
        topic: str,
        partition: int,
        records: list[WireRecord],
        acks: int = -1,
        timeout_ms: int = 30000,
    ) -> int:
        """Returns the base offset assigned by the broker."""
        batch = encode_record_batch(records, base_offset=0)
        body = io.BytesIO()
        _w_string(body, None)  # transactional id
        _w(body, "hi", acks, timeout_ms)
        _w(body, "i", 1)  # one topic
        _w_string(body, topic)
        _w(body, "i", 1)  # one partition
        _w(body, "i", partition)
        _w_bytes(body, batch)
        resp = self._call(PRODUCE, 3, body.getvalue())
        base_offset = -1
        for _ in range(_r(resp, "i")):
            _r_string(resp)
            for _p in range(_r(resp, "i")):
                _pid = _r(resp, "i")
                err = _r(resp, "h")
                base_offset = _r(resp, "q")
                _r(resp, "q")  # log append time
                if err:
                    raise ValueError(f"Produce error {err}")
        _r(resp, "i")  # throttle
        return base_offset

    def list_offsets(
        self, topic: str, partition: int, timestamp: int = -1
    ) -> int:
        """-1 = latest (end offset), -2 = earliest."""
        body = io.BytesIO()
        _w(body, "i", -1)  # replica id
        _w(body, "i", 1)
        _w_string(body, topic)
        _w(body, "i", 1)
        _w(body, "iq", partition, timestamp)
        resp = self._call(LIST_OFFSETS, 1, body.getvalue())
        offset = -1
        for _ in range(_r(resp, "i")):
            _r_string(resp)
            for _p in range(_r(resp, "i")):
                _pid = _r(resp, "i")
                err = _r(resp, "h")
                _r(resp, "q")  # timestamp
                offset = _r(resp, "q")
                if err:
                    raise ValueError(f"ListOffsets error {err}")
        return offset

    def fetch(
        self,
        topic: str,
        partition: int,
        offset: int,
        max_wait_ms: int = 100,
        max_bytes: int = 1 << 22,
    ) -> tuple[list[WireRecord], int]:
        """(records from ``offset``, high watermark)."""
        body = io.BytesIO()
        _w(body, "i", -1)  # replica id
        _w(body, "ii", max_wait_ms, 1)  # max wait, min bytes
        _w(body, "i", max_bytes)
        _w(body, "b", 0)  # isolation level
        _w(body, "i", 1)
        _w_string(body, topic)
        _w(body, "i", 1)
        _w(body, "iqi", partition, offset, max_bytes)
        resp = self._call(FETCH, 4, body.getvalue())
        _r(resp, "i")  # throttle
        records: list[WireRecord] = []
        high_watermark = -1
        for _ in range(_r(resp, "i")):
            _r_string(resp)
            for _p in range(_r(resp, "i")):
                _pid = _r(resp, "i")
                err = _r(resp, "h")
                high_watermark = _r(resp, "q")
                _r(resp, "q")  # last stable offset
                for _a in range(_r(resp, "i")):  # aborted txns
                    _r(resp, "qq")
                record_set = _r_bytes(resp) or b""
                if err:
                    raise ValueError(f"Fetch error {err}")
                records.extend(
                    r
                    for r in decode_record_batches(record_set)
                    if r.offset >= offset
                )
        return records, high_watermark


# -- in-process broker --------------------------------------------------------


class FakeKafkaBroker:
    """A TCP server speaking the same five Kafka APIs (one partition per
    topic, records stored decoded). Frames on the socket are genuine
    Kafka protocol bytes — tests round-trip through real encode/decode on
    both sides of a real socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self.logs: dict[str, list[WireRecord]] = {}
        self._lock = threading.Lock()
        self._closing = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="fake-kafka", daemon=True
        )
        self._thread.start()

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        self._closing.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def __enter__(self) -> "FakeKafkaBroker":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                (length,) = struct.unpack(">i", _recv_exact(conn, 4))
                req = io.BytesIO(_recv_exact(conn, length))
                api_key, api_version, corr = _r(req, "hhi")
                _r_string(req)  # client id
                body = self._dispatch(api_key, api_version, req)
                frame = struct.pack(">i", corr) + body
                conn.sendall(struct.pack(">i", len(frame)) + frame)
        except (ConnectionError, struct.error, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- request handling ----------------------------------------------------

    def _dispatch(self, api_key: int, version: int, req: io.BytesIO) -> bytes:
        if api_key == API_VERSIONS:
            out = io.BytesIO()
            _w(out, "h", 0)
            supported = [
                (PRODUCE, 3, 3),
                (FETCH, 4, 4),
                (LIST_OFFSETS, 1, 1),
                (METADATA, 1, 1),
                (API_VERSIONS, 0, 0),
            ]
            _w(out, "i", len(supported))
            for key, lo, hi in supported:
                _w(out, "hhh", key, lo, hi)
            return out.getvalue()
        if api_key == METADATA:
            n = _r(req, "i")
            names = (
                list(self.logs)
                if n < 0
                else [_r_string(req) for _ in range(n)]
            )
            out = io.BytesIO()
            _w(out, "i", 1)  # one broker
            _w(out, "i", 0)
            _w_string(out, self.host)
            _w(out, "i", self.port)
            _w_string(out, None)  # rack
            _w(out, "i", 0)  # controller
            _w(out, "i", len(names))
            for name in names:
                with self._lock:
                    self.logs.setdefault(name, [])  # auto-create topics
                _w(out, "h", 0)
                _w_string(out, name)
                _w(out, "?", False)
                _w(out, "i", 1)  # one partition
                _w(out, "h", 0)
                _w(out, "i", 0)  # partition id
                _w(out, "i", 0)  # leader
                _w(out, "i", 1)
                _w(out, "i", 0)  # replicas
                _w(out, "i", 1)
                _w(out, "i", 0)  # isr
            return out.getvalue()
        if api_key == PRODUCE:
            _r_string(req)  # transactional id
            _r(req, "hi")  # acks, timeout
            out_topics = []
            for _ in range(_r(req, "i")):
                topic = _r_string(req)
                for _p in range(_r(req, "i")):
                    _pid = _r(req, "i")
                    record_set = _r_bytes(req) or b""
                    records = decode_record_batches(record_set)
                    with self._lock:
                        log = self.logs.setdefault(topic, [])
                        base = len(log)
                        for i, rec in enumerate(records):
                            rec.offset = base + i
                            log.append(rec)
                    out_topics.append((topic, 0, base))
            out = io.BytesIO()
            _w(out, "i", len(out_topics))
            for topic, pid, base in out_topics:
                _w_string(out, topic)
                _w(out, "i", 1)
                _w(out, "i", pid)
                _w(out, "h", 0)
                _w(out, "q", base)
                _w(out, "q", -1)
            _w(out, "i", 0)  # throttle
            return out.getvalue()
        if api_key == LIST_OFFSETS:
            _r(req, "i")  # replica
            answers = []
            for _ in range(_r(req, "i")):
                topic = _r_string(req)
                for _p in range(_r(req, "i")):
                    pid = _r(req, "i")
                    ts = _r(req, "q")
                    with self._lock:
                        end = len(self.logs.get(topic, []))
                    answers.append((topic, pid, 0 if ts == -2 else end))
            out = io.BytesIO()
            _w(out, "i", len(answers))
            for topic, pid, offset in answers:
                _w_string(out, topic)
                _w(out, "i", 1)
                _w(out, "i", pid)
                _w(out, "h", 0)
                _w(out, "q", -1)
                _w(out, "q", offset)
            return out.getvalue()
        if api_key == FETCH:
            _r(req, "i")  # replica
            _r(req, "ii")  # max wait, min bytes
            _r(req, "i")  # max bytes
            _r(req, "b")  # isolation
            answers = []
            for _ in range(_r(req, "i")):
                topic = _r_string(req)
                for _p in range(_r(req, "i")):
                    pid = _r(req, "i")
                    offset = _r(req, "q")
                    _r(req, "i")  # partition max bytes
                    with self._lock:
                        log = list(self.logs.get(topic, []))
                    tail = log[offset:]
                    record_set = (
                        encode_record_batch(tail, base_offset=offset)
                        if tail
                        else b""
                    )
                    answers.append((topic, pid, len(log), record_set))
            out = io.BytesIO()
            _w(out, "i", 0)  # throttle
            _w(out, "i", len(answers))
            for topic, pid, high, record_set in answers:
                _w_string(out, topic)
                _w(out, "i", 1)
                _w(out, "i", pid)
                _w(out, "h", 0)
                _w(out, "q", high)
                _w(out, "q", high)  # last stable offset
                _w(out, "i", 0)  # aborted txns
                _w_bytes(out, record_set)
            return out.getvalue()
        raise ValueError(f"unsupported api key {api_key}")


# -- MessageTransport adapter -------------------------------------------------


class KafkaWireTransport:
    """MessageTransport over :class:`KafkaWireClient` — the production
    Kafka path (pw.io.kafka.read/write default when ``transport=None``).

    Consumes EVERY partition the topic metadata reports, with
    per-partition offsets; produces by key hash (keyless messages
    round-robin). ``mode='streaming'`` never finishes; ``mode='static'``
    snapshots each partition's end offset at first poll and finishes
    once all are reached (the reference's static-read semantics)."""

    def __init__(
        self,
        bootstrap: str,
        topic: str,
        mode: str = "streaming",
        start: str = "earliest",
    ) -> None:
        host, _, port = bootstrap.partition(":")
        self.client = KafkaWireClient(host, int(port or 9092))
        self.topic = topic
        self.mode = mode
        meta = self.client.metadata([topic])
        parts = meta["topics"].get(topic, {}).get("partitions", [])
        self.partitions = sorted(p["partition"] for p in parts) or [0]
        ts = -2 if start == "earliest" else -1
        self._offsets = {
            p: self.client.list_offsets(topic, p, ts)
            for p in self.partitions
        }
        self._stop_at: dict[int, int] | None = None
        self._rr = 0
        self._closed = False

    def produce(self, value: Any, key: Any = None) -> None:
        if isinstance(value, str):
            value = value.encode()
        if isinstance(key, str):
            key = key.encode()
        if key is not None:
            import zlib

            partition = self.partitions[
                zlib.crc32(key) % len(self.partitions)
            ]
        else:
            partition = self.partitions[self._rr % len(self.partitions)]
            self._rr += 1
        self.client.produce(
            self.topic, partition, [WireRecord(value=value, key=key)]
        )

    def poll_messages(self) -> list:
        from pathway_tpu.engine.storage import Message

        if self._stop_at is None and self.mode == "static":
            self._stop_at = {
                p: self.client.list_offsets(self.topic, p, -1)
                for p in self.partitions
            }
        out = []
        for p in self.partitions:
            records, _high = self.client.fetch(
                self.topic, p, self._offsets[p]
            )
            for rec in records:
                self._offsets[p] = rec.offset + 1
                out.append(
                    Message(
                        rec.value,
                        key=rec.key,
                        topic=self.topic,
                        partition=p,
                        offset=rec.offset,
                    )
                )
        return out

    def close(self) -> None:
        self._closed = True
        self.client.close()

    def finished(self) -> bool:
        if self._closed:
            return True
        if self.mode == "static" and self._stop_at is not None:
            return all(
                self._offsets[p] >= end
                for p, end in self._stop_at.items()
            )
        return False
