"""pw.io.s3 — object-store connector (reference: python/pathway/io/s3/,
570 LoC; S3 scanner src/connectors/scanner/s3.rs).

The store is reached through an injected ``client`` implementing
``list_objects(prefix) -> [(key, etag)]`` / ``get_object(key) -> bytes``
(plus ``put_object`` for writes). boto3 adapts in a few lines;
tests/demos use :class:`pathway_tpu.engine.storage.DictObjectStore`.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.connectors import (
    DsvParser,
    IdentityParser,
    JsonLinesFormatter,
    JsonLinesParser,
)
from pathway_tpu.engine.storage import (
    DictObjectStore,
    ObjectStoreReader,
    ObjectStoreWriter,
)
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, converter_for, input_table, require

__all__ = ["read", "write", "AwsS3Settings", "DictObjectStore"]


class AwsS3Settings:
    """Bucket + credentials (reference io/s3 AwsS3Settings)."""

    def __init__(
        self,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        region: str | None = None,
        endpoint: str | None = None,
        with_path_style: bool = False,
    ) -> None:
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style

    def create_client(self) -> Any:
        boto3 = require("boto3", "pw.io.s3")
        s3 = boto3.client(
            "s3",
            aws_access_key_id=self.access_key,
            aws_secret_access_key=self.secret_access_key,
            region_name=self.region,
            endpoint_url=self.endpoint,
        )
        bucket = self.bucket_name

        class _Adapter:
            def list_objects(self, prefix: str):
                out = []
                kwargs = {"Bucket": bucket, "Prefix": prefix}
                while True:  # paginate: one page holds at most 1000 keys
                    resp = s3.list_objects_v2(**kwargs)
                    for item in resp.get("Contents", []):
                        out.append((item["Key"], item["ETag"]))
                    token = resp.get("NextContinuationToken")
                    if not token:
                        return out
                    kwargs["ContinuationToken"] = token

            def get_object(self, key: str) -> bytes:
                return s3.get_object(Bucket=bucket, Key=key)["Body"].read()

            def put_object(self, key: str, data) -> None:
                if isinstance(data, str):
                    data = data.encode("utf-8")
                s3.put_object(Bucket=bucket, Key=key, Body=data)

        return _Adapter()


def _client_of(aws_s3_settings: Any, client: Any) -> Any:
    if client is not None:
        return client
    if aws_s3_settings is None:
        raise ValueError("pass aws_s3_settings= or client=")
    return aws_s3_settings.create_client()


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "json",  # noqa: A002
    schema: schema_mod.SchemaMetaclass | None = None,
    mode: str = "streaming",
    client: Any = None,
    with_metadata: bool = False,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Scan objects under ``path`` (a key prefix). Each object is parsed per
    ``format`` (csv/json/plaintext/binary); object rewrites replace their
    previous rows, deletions retract them."""
    store = _client_of(aws_s3_settings, client)
    if format in ("plaintext", "binary", "plaintext_by_object"):
        schema = schema_mod.schema_from_types(
            data=bytes if format == "binary" else str
        )
    if schema is None:
        raise ValueError("schema= is required for csv/json formats")
    dtypes = schema.dtypes()
    binary = format == "binary"

    def make_parser(names):
        if format == "csv":
            return DsvParser(
                names, converters=[converter_for(dtypes[n]) for n in names]
            )
        if format == "json":
            return JsonLinesParser(names)
        if format == "plaintext":
            return IdentityParser(split_lines=True)
        return IdentityParser(binary=binary, split_lines=False)

    return input_table(
        schema,
        lambda: ObjectStoreReader(store, path, mode=mode, binary=binary),
        make_parser,
        source_name=f"s3:{path}",
        with_metadata=with_metadata,
        persistent_id=persistent_id,
    )


def write(
    table: Table,
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "json",  # noqa: A002
    client: Any = None,
    **kwargs: Any,
) -> None:
    """Write one JSON-lines object per commit under ``path``."""
    if format != "json":
        raise ValueError(f"unsupported s3 write format {format!r}")
    store = _client_of(aws_s3_settings, client)

    def make_writer(column_names):
        return ObjectStoreWriter(store, path, JsonLinesFormatter(), column_names)

    attach_writer(table, make_writer)
