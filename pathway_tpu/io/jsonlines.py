"""pw.io.jsonlines (reference: python/pathway/io/jsonlines/__init__.py)."""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io import fs as _fs


def read(
    path: str | os.PathLike,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    **kwargs: Any,
) -> Table:
    return _fs.read(
        path,
        format="json",
        schema=schema,
        mode=mode,
        with_metadata=with_metadata,
        **kwargs,
    )


def write(table: Table, filename: str | os.PathLike, **kwargs: Any) -> None:
    _fs.write(table, filename, format="json", **kwargs)
