"""pw.io.python — user-defined push sources.

(reference: python/pathway/io/python/__init__.py, 527 LoC — ConnectorSubject
:49 with next()/commit()/close(), backed by the engine PythonSubject.)
Here the subject runs in a thread writing parsed events to a queue drained by
the streaming run loop.
"""

from __future__ import annotations

import json as _json
import threading
from typing import Any, Sequence

from pathway_tpu.engine.connectors import INSERT, DELETE, ParsedEvent, Parser, QueueReader
from pathway_tpu.engine.value import Json
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table


class ConnectorSubject:
    """Subclass and implement ``run()``, calling ``self.next(**fields)``."""

    def __init__(self) -> None:
        self._reader = QueueReader()
        self._thread: threading.Thread | None = None

    # -- user API -----------------------------------------------------------

    def next(self, **kwargs: Any) -> None:
        self._reader.push(("insert", kwargs))

    def next_json(self, message: dict | str) -> None:
        if isinstance(message, str):
            message = _json.loads(message)
        self.next(**message)

    def next_str(self, message: str) -> None:
        self.next(data=message)

    def next_bytes(self, message: bytes) -> None:
        self.next(data=message)

    def _remove(self, **kwargs: Any) -> None:
        self._reader.push(("delete", kwargs))

    def commit(self) -> None:
        self._reader.push(("commit", None))

    def close(self) -> None:
        self._reader.close()

    def run(self) -> None:
        raise NotImplementedError

    # -- engine integration --------------------------------------------------

    def _start(self) -> None:
        def runner() -> None:
            try:
                self.run()
            finally:
                self.close()

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()


class _SubjectParser(Parser):
    def __init__(self, column_names: Sequence[str], dtypes: dict) -> None:
        super().__init__(column_names)
        self.dtypes = dtypes

    def parse(self, payload: Any) -> list[ParsedEvent]:
        kind, fields = payload
        if kind == "commit" or fields is None:
            return []
        values = []
        for name in self.column_names:
            v = fields.get(name)
            if isinstance(v, (dict, list)):
                v = Json(v)
            values.append(v)
        return [ParsedEvent(INSERT if kind == "insert" else DELETE, tuple(values))]


def read(
    subject: ConnectorSubject,
    *,
    schema: schema_mod.SchemaMetaclass,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    dtypes = schema.dtypes()

    started = False

    def make_reader():
        nonlocal started
        if not started:
            subject._start()
            started = True
        return subject._reader

    def make_parser(names):
        return _SubjectParser(names, dtypes)

    return input_table(
        schema,
        make_reader,
        make_parser,
        source_name="python-connector",
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )
