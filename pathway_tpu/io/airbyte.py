"""pw.io.airbyte — run Airbyte source connectors (reference:
python/pathway/io/airbyte/__init__.py + vendored airbyte_serverless).

The reference executes connector images via Docker or GCP Cloud Run, or
pip-installed ``airbyte-<name>`` packages in a venv. Docker is not
available in this image, so the **serverless executable path** is
implemented natively: :class:`ExecutableAirbyteSource` launches any
local command speaking the Airbyte protocol on stdout
(``spec`` / ``check`` / ``discover`` / ``read`` with JSON-line
``RECORD``/``STATE`` messages) — a pip-installed connector's
entry point, ``python -m source_x``, or a test script. Rows match the
reference's ``_AirbyteRecordSchema``: one JSON ``data`` column per
record. Incremental streams carry Airbyte STATE between syncs (and
through persistence); full-refresh streams replace the previous sync's
rows. ``pw.io.airbyte.read_records`` still replays captured streams.
"""

from __future__ import annotations

import json
import os
import subprocess
import tempfile
import time as _time
from typing import Any, Iterable, Sequence

from pathway_tpu.engine.connectors import INSERT, ParsedEvent, Parser, Reader
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table

FULL_REFRESH_SYNC_MODE = "full_refresh"
INCREMENTAL_SYNC_MODE = "incremental"


class ExecutableAirbyteSource:
    """Drive a local Airbyte-protocol source executable.

    ``command`` is the argv prefix (e.g. ``["python", "-m", "source_faker"]``
    or a console-script path); protocol subcommands and ``--config`` /
    ``--catalog`` / ``--state`` files are appended per call.
    """

    def __init__(
        self,
        command: Sequence[str],
        config: dict | None,
        streams: Sequence[str],
        env_vars: dict[str, str] | None = None,
    ) -> None:
        self.command = list(command)
        self.config = config or {}
        self.streams = list(streams)
        self.env_vars = dict(env_vars or {})
        self._catalog: dict | None = None

    def _run(self, args: list[str]) -> list[dict]:
        env = {**os.environ, **self.env_vars}
        proc = subprocess.run(
            self.command + args,
            capture_output=True,
            text=True,
            env=env,
            timeout=600,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"airbyte source {self.command} {args[0]} failed "
                f"(rc={proc.returncode}): {proc.stderr[-2000:]}"
            )
        messages = []
        for line in proc.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                messages.append(json.loads(line))
            except ValueError:
                continue  # connectors may log non-JSON lines to stdout
        return messages

    def _with_config(self, extra: list[str]) -> list[dict]:
        with tempfile.TemporaryDirectory(prefix="pw-airbyte-") as tmp:
            config_path = os.path.join(tmp, "config.json")
            with open(config_path, "w") as f:
                json.dump(self.config, f)
            resolved = [a.replace("{config}", config_path) for a in extra]
            return self._run(resolved)

    def spec(self) -> dict:
        for msg in self._run(["spec"]):
            if msg.get("type") == "SPEC":
                return msg["spec"]
        raise RuntimeError("source emitted no SPEC message")

    def check(self) -> bool:
        for msg in self._with_config(["check", "--config", "{config}"]):
            if msg.get("type") == "CONNECTION_STATUS":
                return msg["connectionStatus"]["status"] == "SUCCEEDED"
        raise RuntimeError("source emitted no CONNECTION_STATUS message")

    def discover(self) -> dict:
        if self._catalog is None:
            for msg in self._with_config(
                ["discover", "--config", "{config}"]
            ):
                if msg.get("type") == "CATALOG":
                    self._catalog = msg["catalog"]
                    break
            else:
                raise RuntimeError("source emitted no CATALOG message")
        return self._catalog

    @property
    def configured_catalog(self) -> dict:
        catalog = self.discover()
        by_name = {s["name"]: s for s in catalog.get("streams", [])}
        configured = []
        for name in self.streams:
            stream = by_name.get(name)
            if stream is None:
                raise ValueError(
                    f"stream {name!r} not in the source catalog "
                    f"(available: {sorted(by_name)})"
                )
            supported = stream.get("supported_sync_modes") or [
                FULL_REFRESH_SYNC_MODE
            ]
            sync_mode = (
                INCREMENTAL_SYNC_MODE
                if INCREMENTAL_SYNC_MODE in supported
                else FULL_REFRESH_SYNC_MODE
            )
            configured.append(
                {
                    "stream": stream,
                    "sync_mode": sync_mode,
                    "destination_sync_mode": "append",
                }
            )
        return {"streams": configured}

    def extract(
        self, state: list | dict | None = None
    ) -> tuple[list[dict], Any]:
        """One sync: ``(records, final_state)`` for the configured
        streams; ``state`` resumes an incremental sync."""
        with tempfile.TemporaryDirectory(prefix="pw-airbyte-") as tmp:
            config_path = os.path.join(tmp, "config.json")
            catalog_path = os.path.join(tmp, "catalog.json")
            with open(config_path, "w") as f:
                json.dump(self.config, f)
            with open(catalog_path, "w") as f:
                json.dump(self.configured_catalog, f)
            args = [
                "read",
                "--config",
                config_path,
                "--catalog",
                catalog_path,
            ]
            if state is not None:
                state_path = os.path.join(tmp, "state.json")
                with open(state_path, "w") as f:
                    json.dump(state, f)
                args += ["--state", state_path]
            wanted = set(self.streams)
            records: list[dict] = []
            # per-stream STATE messages accumulate (last wins per stream);
            # a single legacy data blob passes through as-is — overwriting
            # with only the last message would lose every other stream's
            # cursor between syncs
            stream_states: dict[str, dict] = {}
            legacy_state: Any = None
            for msg in self._run(args):
                if msg.get("type") == "RECORD":
                    record = msg["record"]
                    if record.get("stream") in wanted:
                        records.append(record)
                elif msg.get("type") == "STATE":
                    st = msg["state"]
                    if st.get("type") == "STREAM" and "stream" in st:
                        desc = json.dumps(
                            st["stream"].get("stream_descriptor", {}),
                            sort_keys=True,
                        )
                        stream_states[desc] = st
                    else:
                        legacy_state = st.get("data", st)
            if stream_states:
                final_state: Any = list(stream_states.values())
            elif legacy_state is not None:
                final_state = legacy_state
            else:
                final_state = state
            return records, final_state


class _AirbyteReader(Reader):
    """Poll the source; incremental syncs append with carried STATE,
    full-refresh syncs replace the previous sync's rows. Sync modes are
    homogeneous per read() — the reference enforces the same rule."""

    def __init__(
        self,
        source: ExecutableAirbyteSource,
        mode: str,
        refresh_interval_s: float,
    ) -> None:
        self.source = source
        self.mode = mode
        self.refresh_interval_s = refresh_interval_s
        self._state: Any = None
        self._last_sync = 0.0
        self._first = True
        modes = {
            s["sync_mode"]
            for s in source.configured_catalog["streams"]
        }
        if len(modes) > 1:
            # mixed modes cannot share one reader: full-refresh streams
            # must replace their previous sync while incremental ones
            # append (reference io/airbyte/__init__.py raises identically)
            raise ValueError(
                "all streams within one pw.io.airbyte.read must share a "
                f"sync_mode; got {sorted(modes)} — split into one read() "
                "per mode"
            )
        self._incremental = modes == {INCREMENTAL_SYNC_MODE}
        # full-refresh polls re-read the same source: later syncs replace.
        # Each stream's WHOLE sync is one payload (one source id), so the
        # replacement unit is the stream snapshot, not a single record.
        self.replaces_sources = not self._incremental

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        now = _time.monotonic()
        if not self._first and now - self._last_sync < self.refresh_interval_s:
            return [], False
        self._last_sync = now
        self._first = False
        records, self._state = self.source.extract(
            self._state if self._incremental else None
        )
        # seed EVERY configured stream: a full-refresh sync that returns
        # zero records must still emit an empty replacing payload so the
        # previous snapshot's rows retract
        by_stream: dict[str, list[dict]] = {
            s["stream"]["name"]: []
            for s in self.source.configured_catalog["streams"]
        }
        for record in records:
            by_stream.setdefault(record.get("stream", ""), []).append(record)
        entries = [
            (recs, f"airbyte:{stream}", {"stream": stream})
            for stream, recs in by_stream.items()
            if recs or not self._incremental
        ]
        return entries, self.mode == "static"

    def state(self) -> dict:
        return {"airbyte_state": self._state}

    def restore_state(self, state: dict) -> None:
        self._state = state.get("airbyte_state")


class _AirbyteParser(Parser):
    def __init__(self) -> None:
        super().__init__(["data"])

    def parse(self, payload: Any) -> list[ParsedEvent]:
        from pathway_tpu.engine.value import Json

        return [
            ParsedEvent(INSERT, (Json(record.get("data", {})),))
            for record in payload
        ]


def _load_config(config_file_path: str) -> tuple[dict, list[str] | None]:
    """(source config, optional command from the file). Accepts the
    airbyte-serverless YAML layout (``source: {config:, exec:}``) and
    plain JSON/YAML config objects."""
    with open(config_file_path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        import yaml

        doc = yaml.safe_load(text)
    if not isinstance(doc, dict):
        raise ValueError("airbyte config file must hold an object")
    source = doc.get("source")
    if isinstance(source, dict):
        command = source.get("exec")
        if isinstance(command, str):
            command = command.split()
        return source.get("config") or {}, command
    return doc, None


def venv_connector_command(
    connector_name: str,
    *,
    venv_path: str | None = None,
    pip_extra_args: Sequence[str] | None = None,
    reuse: bool = True,
) -> list[str]:
    """Create a virtualenv, pip-install ``airbyte-<name>``, and return
    the connector's entry-point argv (reference VenvAirbyteSource,
    third_party/airbyte_serverless/sources.py:137-170).

    pip needs a package index; this environment may be OFFLINE. The
    offline paths, all first-class:

    - ``venv_path=`` pointing at a venv where the connector entry point
      already exists (``reuse=True`` skips pip entirely);
    - ``pip_extra_args=["--no-index", "--find-links", <wheel dir>]``
      installing from local wheels;
    - or skip this helper and pass ``connector_command=`` naming any
      local Airbyte-protocol executable.
    """
    import os
    import pathlib
    import subprocess
    import tempfile
    import venv as venv_mod

    name = connector_name.removeprefix("airbyte-")
    root = pathlib.Path(
        venv_path
        if venv_path is not None
        else tempfile.mkdtemp(prefix=f"pw-airbyte-{name}-")
    )
    exe = root / "bin" / name
    if reuse and exe.exists():
        return [os.fspath(exe)]
    if not (root / "bin" / "pip").exists():
        venv_mod.create(root, with_pip=True)
    cmd = [
        os.fspath(root / "bin" / "pip"),
        "install",
        *(pip_extra_args or ()),
        f"airbyte-{name}",
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=600
        )
    except subprocess.TimeoutExpired as exc:
        raise RuntimeError(
            f"pip install airbyte-{name} timed out after 600s — this "
            f"environment likely has no network access. Offline options: "
            f"pip_extra_args=['--no-index', '--find-links', '<wheel "
            f"dir>'], venv_path= at a venv with the connector already "
            f"installed, or connector_command= naming a local "
            f"Airbyte-protocol executable."
        ) from exc
    if proc.returncode != 0:
        tail = (proc.stdout + proc.stderr)[-2000:]
        raise RuntimeError(
            f"failed to install airbyte-{name} into {root} (pip exited "
            f"{proc.returncode}).\n--- pip output (tail) ---\n{tail}\n"
            f"If PyPI is unreachable from this environment, use one of "
            f"the offline options: pip_extra_args=['--no-index', "
            f"'--find-links', '<dir with wheels>'], venv_path= at a "
            f"venv where the connector is already installed, or "
            f"connector_command= naming a local Airbyte-protocol "
            f"executable."
        )
    if not exe.exists():
        raise RuntimeError(
            f"airbyte-{name} installed but its entry point {exe} is "
            f"missing; pass connector_command= explicitly"
        )
    return [os.fspath(exe)]


def read(
    config_file_path: str,
    streams: Sequence[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    connector_command: Sequence[str] | str | None = None,
    env_vars: dict[str, str] | None = None,
    refresh_interval_ms: int = 60000,
    persistent_id: str | None = None,
    connector_name: str | None = None,
    venv_path: str | None = None,
    pip_extra_args: Sequence[str] | None = None,
    **kwargs: Any,
) -> Table:
    """Run a local Airbyte source and stream its records (one JSON
    ``data`` column per record — the reference's _AirbyteRecordSchema).

    ``execution_type="local"``: ``connector_command`` names the
    executable (argv list or shell-split string); it may also come from
    the config file's ``source.exec`` field.
    ``execution_type="venv"`` (the reference's pypi method): a
    virtualenv is created and ``airbyte-<connector_name>`` installed
    into it via :func:`venv_connector_command` — with explicit offline
    fallbacks (pre-installed ``venv_path=``, local-wheel
    ``pip_extra_args=``). Docker/Cloud-Run execution stays unavailable
    in this environment."""
    if execution_type not in ("local", "venv", "pypi"):
        raise NotImplementedError(
            f"execution_type={execution_type!r}: 'local' executables and "
            "'venv' (pip-installed connectors) are supported here (no "
            "docker/Cloud Run runtime)"
        )
    config, file_command = _load_config(config_file_path)
    if execution_type in ("venv", "pypi"):
        if connector_name is None:
            raise ValueError(
                "execution_type='venv' needs connector_name= (e.g. "
                "'source-faker')"
            )
        connector_command = venv_connector_command(
            connector_name,
            venv_path=venv_path,
            pip_extra_args=pip_extra_args,
        )
    if connector_command is None:
        connector_command = file_command
    if connector_command is None:
        raise ValueError(
            "no connector command: pass connector_command= or put "
            "'source: {exec: ...}' in the config file"
        )
    if isinstance(connector_command, str):
        connector_command = connector_command.split()
    source = ExecutableAirbyteSource(
        connector_command, config, streams, env_vars=env_vars
    )
    schema = schema_mod.schema_from_types(data=dict)

    return input_table(
        schema,
        lambda: _AirbyteReader(source, mode, refresh_interval_ms / 1000.0),
        lambda names: _AirbyteParser(),
        source_name=f"airbyte:{','.join(streams)}",
        persistent_id=persistent_id,
    )


def read_records(records: Iterable[dict], stream: str = "stream") -> Table:
    """Replay a captured Airbyte record stream (each record a dict with the
    stream's fields) as a static table."""
    import pathway_tpu as pw

    records = [r for r in records]
    if not records:
        raise ValueError("no records")
    names = sorted({k for r in records for k in r})
    schema = schema_mod.schema_from_types(**{n: Any for n in names})
    rows = [tuple(r.get(n) for n in names) for r in records]
    return pw.debug.table_from_rows(schema, rows)
