"""pw.io.airbyte — run Airbyte source connectors (reference:
python/pathway/io/airbyte/__init__.py:107 — executes connector images via
Docker or Cloud Run). Requires Docker, which this image cannot assume; the
entry point is kept and gated. A pre-captured Airbyte stream (list of
record dicts) can be replayed through ``read_records``."""

from __future__ import annotations

from typing import Any, Iterable, Sequence

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table


def read(
    config_file_path: str,
    streams: Sequence[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    **kwargs: Any,
) -> Table:
    raise NotImplementedError(
        "pw.io.airbyte runs connector docker images (reference "
        "io/airbyte/__init__.py:107); no docker runtime is available here. "
        "Replay captured records with pw.io.airbyte.read_records."
    )


def read_records(records: Iterable[dict], stream: str = "stream") -> Table:
    """Replay a captured Airbyte record stream (each record a dict with the
    stream's fields) as a static table."""
    import pathway_tpu as pw

    records = [r for r in records]
    if not records:
        raise ValueError("no records")
    names = sorted({k for r in records for k in r})
    schema = schema_mod.schema_from_types(**{n: Any for n in names})
    rows = [tuple(r.get(n) for n in names) for r in records]
    return pw.debug.table_from_rows(schema, rows)
