"""pw.io.sqlite — read a SQLite table as a change stream
(reference: python/pathway/io/sqlite/__init__.py, SqliteReader
src/connectors/data_storage.rs:1396)."""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.engine.storage import SqliteReader, TransparentParser
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table


def read(
    path: str | os.PathLike,
    table_name: str,
    schema: schema_mod.SchemaMetaclass,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Table:
    """Poll ``table_name`` in the SQLite database at ``path``; inserts,
    updates and deletions of rows (keyed by rowid) become engine diffs."""
    column_names = schema.column_names()
    path = os.fspath(path)

    return input_table(
        schema,
        lambda: SqliteReader(path, table_name, column_names, mode=mode),
        lambda names: TransparentParser(names),
        source_name=f"sqlite:{path}:{table_name}",
        autocommit_duration_ms=autocommit_duration_ms,
    )
