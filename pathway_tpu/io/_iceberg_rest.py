"""Iceberg REST catalog: client + in-process fake server.

The reference reads/writes Iceberg through a REST catalog service
(src/connectors/data_lake/iceberg.rs via iceberg-rust). This module
implements the catalog subset that table streaming needs, with the REST
spec's endpoint shapes (rest-catalog-open-api.yaml):

- ``GET  {prefix}/v1/config``
- ``POST {prefix}/v1/namespaces``                       (create namespace)
- ``POST {prefix}/v1/namespaces/{ns}/tables``           (create table)
- ``GET  {prefix}/v1/namespaces/{ns}/tables/{table}``   (load table)
- ``POST {prefix}/v1/namespaces/{ns}/tables/{table}``   (commit: the
  spec's CommitTableRequest ``{requirements, updates}`` with
  assert-table-uuid / assert-ref-snapshot-id requirements and
  add-snapshot / set-snapshot-ref updates; version conflicts -> 409)

The fake server holds table metadata documents (the catalog's job); data
and manifest files live under its ``warehouse`` directory on the local
filesystem, where both the writer and reader reach them — the same
split a real deployment has between the catalog service and the object
store. Commit concurrency is enforced server-side: a stale
``assert-ref-snapshot-id`` gets 409 Conflict and the client surfaces it,
like the hadoop catalog's lost-rename race.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Sequence


class IcebergRestError(Exception):
    """Catalog-reported error; ``code`` carries the HTTP status."""

    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"{code}: {message}")
        self.code = code


class RestCatalogClient:
    """Minimal REST catalog client over urllib (stdlib-only)."""

    def __init__(
        self,
        uri: str,
        *,
        token: str | None = None,
        timeout: float = 20.0,
    ) -> None:
        self.uri = uri.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _request(
        self, method: str, path: str, body: dict | None = None
    ) -> dict:
        url = f"{self.uri}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode("utf-8", "replace")
            try:
                detail = json.loads(detail)["error"]["message"]
            except Exception:  # noqa: BLE001 — keep the raw body
                pass
            raise IcebergRestError(exc.code, detail) from None
        return json.loads(payload) if payload else {}

    # -- endpoints ----------------------------------------------------------

    def config(self) -> dict:
        return self._request("GET", "/v1/config")

    def create_namespace(self, namespace: Sequence[str]) -> None:
        try:
            self._request(
                "POST", "/v1/namespaces", {"namespace": list(namespace)}
            )
        except IcebergRestError as exc:
            if exc.code != 409:  # AlreadyExists is fine
                raise

    def load_table(
        self, namespace: Sequence[str], table: str
    ) -> dict | None:
        """LoadTableResult ``{metadata-location, metadata}`` or None."""
        try:
            return self._request(
                "GET",
                f"/v1/namespaces/{'.'.join(namespace)}/tables/{table}",
            )
        except IcebergRestError as exc:
            if exc.code == 404:
                return None
            raise

    def create_table(
        self,
        namespace: Sequence[str],
        table: str,
        schema: dict,
        location: str | None = None,
    ) -> dict:
        body: dict = {"name": table, "schema": schema}
        if location is not None:
            body["location"] = location
        return self._request(
            "POST", f"/v1/namespaces/{'.'.join(namespace)}/tables", body
        )

    def commit_table(
        self,
        namespace: Sequence[str],
        table: str,
        requirements: list[dict],
        updates: list[dict],
    ) -> dict:
        return self._request(
            "POST",
            f"/v1/namespaces/{'.'.join(namespace)}/tables/{table}",
            {"requirements": requirements, "updates": updates},
        )


# -- fake server -------------------------------------------------------------


class FakeIcebergRestServer:
    """In-process REST catalog: metadata documents in memory, table
    locations under ``warehouse`` on the local filesystem."""

    def __init__(
        self, warehouse: str, *, token: str | None = None
    ) -> None:
        self.warehouse = os.fspath(warehouse)
        self.token = token
        self.namespaces: set[str] = set()
        #: "ns.table" -> metadata document (the catalog's copy of truth)
        self.tables: dict[str, dict] = {}
        self.requests: list[tuple[str, str]] = []  # (method, path) log
        self.conflicts = 0
        self._lock = threading.Lock()
        catalog = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, message: str) -> None:
                self._reply(
                    code,
                    {
                        "error": {
                            "message": message,
                            "type": "CatalogError",
                            "code": code,
                        }
                    },
                )

            def _authed(self) -> bool:
                if catalog.token is None:
                    return True
                got = self.headers.get("Authorization", "")
                if got == f"Bearer {catalog.token}":
                    return True
                self._error(401, "invalid token")
                return False

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                with catalog._lock:
                    catalog.requests.append(("GET", self.path))
                if not self._authed():
                    return
                parts = self.path.strip("/").split("/")
                if parts == ["v1", "config"]:
                    self._reply(
                        200,
                        {"defaults": {}, "overrides": {
                            "warehouse": catalog.warehouse
                        }},
                    )
                    return
                if (
                    len(parts) == 5
                    and parts[:2] == ["v1", "namespaces"]
                    and parts[3] == "tables"
                ):
                    key = f"{parts[2]}.{parts[4]}"
                    with catalog._lock:
                        meta = catalog.tables.get(key)
                    if meta is None:
                        self._error(404, f"table {key} not found")
                        return
                    self._reply(
                        200,
                        {
                            "metadata-location": f"{catalog.uri()}"
                            f"/metadata/{key}",
                            "metadata": meta,
                        },
                    )
                    return
                self._error(404, f"no route {self.path}")

            def do_POST(self) -> None:  # noqa: N802 — http.server API
                with catalog._lock:
                    catalog.requests.append(("POST", self.path))
                if not self._authed():
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = (
                    json.loads(self.rfile.read(length)) if length else {}
                )
                parts = self.path.strip("/").split("/")
                if parts == ["v1", "namespaces"]:
                    ns = ".".join(body["namespace"])
                    with catalog._lock:
                        if ns in catalog.namespaces:
                            self._error(409, f"namespace {ns} exists")
                            return
                        catalog.namespaces.add(ns)
                    self._reply(200, {"namespace": body["namespace"]})
                    return
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "namespaces"]
                    and parts[3] == "tables"
                ):
                    self._create_table(parts[2], body)
                    return
                if (
                    len(parts) == 5
                    and parts[:2] == ["v1", "namespaces"]
                    and parts[3] == "tables"
                ):
                    self._commit_table(f"{parts[2]}.{parts[4]}", body)
                    return
                self._error(404, f"no route {self.path}")

            def _create_table(self, ns: str, body: dict) -> None:
                name = body["name"]
                key = f"{ns}.{name}"
                with catalog._lock:
                    if key in catalog.tables:
                        self._error(409, f"table {key} exists")
                        return
                    location = body.get("location") or os.path.join(
                        catalog.warehouse, *ns.split("."), name
                    )
                    import time as _t
                    import uuid as _uuid

                    meta = {
                        "format-version": 2,
                        "table-uuid": str(_uuid.uuid4()),
                        "location": location,
                        "last-sequence-number": 0,
                        "last-updated-ms": int(_t.time() * 1000),
                        "last-column-id": len(
                            body["schema"].get("fields", [])
                        ),
                        "current-schema-id": 0,
                        "schemas": [body["schema"]],
                        "default-spec-id": 0,
                        "partition-specs": [{"spec-id": 0, "fields": []}],
                        "last-partition-id": 999,
                        "default-sort-order-id": 0,
                        "sort-orders": [{"order-id": 0, "fields": []}],
                        "properties": body.get("properties", {}),
                        "current-snapshot-id": -1,
                        "snapshots": [],
                        "snapshot-log": [],
                        "metadata-log": [],
                        "refs": {},
                    }
                    catalog.tables[key] = meta
                os.makedirs(os.path.join(location, "metadata"), exist_ok=True)
                os.makedirs(os.path.join(location, "data"), exist_ok=True)
                self._reply(
                    200,
                    {
                        "metadata-location": f"{catalog.uri()}"
                        f"/metadata/{key}",
                        "metadata": meta,
                    },
                )

            def _commit_table(self, key: str, body: dict) -> None:
                with catalog._lock:
                    meta = catalog.tables.get(key)
                    if meta is None:
                        self._error(404, f"table {key} not found")
                        return
                    for req in body.get("requirements", ()):
                        kind = req.get("type")
                        if kind == "assert-table-uuid":
                            if req.get("uuid") != meta["table-uuid"]:
                                catalog.conflicts += 1
                                self._error(409, "table uuid mismatch")
                                return
                        elif kind == "assert-ref-snapshot-id":
                            current = meta.get("refs", {}).get(
                                req.get("ref", "main"), {}
                            ).get("snapshot-id")
                            if current != req.get("snapshot-id"):
                                catalog.conflicts += 1
                                self._error(
                                    409,
                                    f"ref {req.get('ref')} is at "
                                    f"{current}, not "
                                    f"{req.get('snapshot-id')}",
                                )
                                return
                        else:
                            self._error(
                                400, f"unsupported requirement {kind!r}"
                            )
                            return
                    for upd in body.get("updates", ()):
                        action = upd.get("action")
                        if action == "add-snapshot":
                            snap = upd["snapshot"]
                            meta["snapshots"].append(snap)
                            meta["last-sequence-number"] = max(
                                meta["last-sequence-number"],
                                snap.get("sequence-number", 0),
                            )
                            meta["last-updated-ms"] = snap.get(
                                "timestamp-ms",
                                meta["last-updated-ms"],
                            )
                            meta["snapshot-log"].append(
                                {
                                    "snapshot-id": snap["snapshot-id"],
                                    "timestamp-ms": snap.get(
                                        "timestamp-ms", 0
                                    ),
                                }
                            )
                        elif action == "set-snapshot-ref":
                            meta.setdefault("refs", {})[
                                upd.get("ref-name", "main")
                            ] = {
                                "snapshot-id": upd["snapshot-id"],
                                "type": upd.get("type", "branch"),
                            }
                            meta["current-snapshot-id"] = upd[
                                "snapshot-id"
                            ]
                        else:
                            self._error(
                                400, f"unsupported update {action!r}"
                            )
                            return
                    out = dict(meta)
                self._reply(
                    200,
                    {
                        "metadata-location": f"{catalog.uri()}"
                        f"/metadata/{key}",
                        "metadata": out,
                    },
                )

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
