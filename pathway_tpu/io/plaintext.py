"""pw.io.plaintext (reference: python/pathway/io/plaintext/__init__.py)."""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.internals.table import Table
from pathway_tpu.io import fs as _fs


def read(path: str | os.PathLike, *, mode: str = "streaming", with_metadata: bool = False, **kwargs: Any) -> Table:
    return _fs.read(path, format="plaintext", mode=mode, with_metadata=with_metadata, **kwargs)
