"""pw.io.debezium — CDC change streams in Debezium envelope format
(reference: python/pathway/io/debezium/__init__.py:20; DebeziumMessageParser
src/connectors/data_format.rs:1053)."""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.engine.formats import DebeziumParser
from pathway_tpu.engine.storage import MessageQueueReader
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table


def read(
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    *,
    schema: schema_mod.SchemaMetaclass,
    db_type: str = "postgres",
    transport: Any = None,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Consume a Debezium CDC topic. ``db_type``: 'postgres' (full
    before/after images -> native diffs) or 'mongodb' (after images only ->
    upsert stream). Keys come from the message-key payload using the
    schema's primary key columns."""
    if transport is None:
        from pathway_tpu.io.kafka import _default_transport

        transport = _default_transport(rdkafka_settings or {}, topic_name)
    pk: Sequence[str] | None = schema.primary_key_columns() or None

    return input_table(
        schema,
        lambda: MessageQueueReader(transport),
        lambda names: DebeziumParser(names, key_field_names=pk, db_type=db_type),
        source_name=f"debezium:{topic_name}",
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )
