"""pw.io.logstash — stream updates into Logstash's HTTP input plugin
(reference: python/pathway/io/logstash/__init__.py — a thin wrapper over
the HTTP writer)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table
from pathway_tpu.io.http import RetryPolicy, write as _http_write


def write(
    table: Table,
    endpoint: str,
    n_retries: int = 0,
    retry_policy: RetryPolicy | None = None,
    *,
    request_fn: Callable[[str, dict], Any] | None = None,
    **kwargs: Any,
) -> None:
    _http_write(
        table,
        endpoint,
        n_retries=n_retries,
        retry_policy=retry_policy,
        request_fn=request_fn,
    )
