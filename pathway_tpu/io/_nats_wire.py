"""NATS wire protocol: client + in-process fake server over real frames.

The NATS client protocol is line-oriented (nats.io protocol docs):

- server greets with ``INFO {json}\\r\\n``; client answers
  ``CONNECT {json}\\r\\n``
- ``PING\\r\\n`` / ``PONG\\r\\n`` keepalives (either direction)
- publish:   ``PUB <subject> [reply-to] <#bytes>\\r\\n<payload>\\r\\n``
- subscribe: ``SUB <subject> [queue-group] <sid>\\r\\n``
- delivery:  ``MSG <subject> <sid> [reply-to] <#bytes>\\r\\n<payload>\\r\\n``
- ``+OK`` / ``-ERR 'reason'`` in verbose mode

Reference: the pathway NATS reader/writer
(src/connectors/data_storage.rs NATS variants,
python/pathway/io/nats/__init__.py) run over the same protocol via the
nats client library; here the frames themselves are implemented, like
the Kafka (io/_kafka_wire.py) and Postgres (io/_pg_wire.py) modules.
The fake server routes PUB frames to matching subscriptions (exact
subjects plus the ``*`` single-token and ``>`` tail wildcards) so
read/write round-trips exercise genuine protocol traffic.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Any

from pathway_tpu.engine.storage import Message


class NatsError(Exception):
    """-ERR from the server or a protocol violation."""


class _LineReader:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self._buf = b""

    def read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise NatsError("connection closed by peer")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise NatsError("connection closed by peer")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


def _subject_matches(pattern: str, subject: str) -> bool:
    """NATS subject matching: ``*`` = one token, ``>`` = rest."""
    p_toks = pattern.split(".")
    s_toks = subject.split(".")
    for i, p in enumerate(p_toks):
        if p == ">":
            return len(s_toks) > i  # '>' stands for ONE OR MORE tokens
        if i >= len(s_toks):
            return False
        if p != "*" and p != s_toks[i]:
            return False
    return len(p_toks) == len(s_toks)


class NatsConnection:
    """Wire-level NATS client: INFO/CONNECT handshake, PUB/SUB/MSG."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 4222,
        *,
        token: str | None = None,
        user: str | None = None,
        password: str | None = None,
        verbose: bool = False,
        connect_timeout: float = 10.0,
    ) -> None:
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self._reader = _LineReader(self.sock)
        self._lock = threading.Lock()
        self.verbose = verbose
        line = self._reader.read_line()
        if not line.startswith(b"INFO "):
            raise NatsError(f"expected INFO, got {line[:40]!r}")
        self.server_info = json.loads(line[5:])
        options: dict[str, Any] = {
            "verbose": verbose,
            "pedantic": False,
            "lang": "pathway-tpu",
            "version": "1.0",
            "protocol": 0,
        }
        if token is not None:
            options["auth_token"] = token
        if user is not None:
            options["user"] = user
            options["pass"] = password
        self._send(f"CONNECT {json.dumps(options)}\r\n".encode())
        # PING/PONG completes the handshake and surfaces auth errors
        self._send(b"PING\r\n")
        self._await_pong()
        #: messages delivered for our subscriptions: (subject, sid, payload)
        self.inbox: list[tuple[str, int, bytes]] = []

    def _send(self, data: bytes) -> None:
        with self._lock:
            # pwc-ok: PWC403 — this lock exists to serialize socket writers
            self.sock.sendall(data)

    def _await_pong(self) -> None:
        while True:
            line = self._handle_line(self._reader.read_line())
            if line == b"PONG":
                return

    def _handle_line(self, line: bytes) -> bytes:
        """Process one server line; MSG payloads land in the inbox."""
        if line.startswith(b"-ERR"):
            raise NatsError(line.decode("utf-8", "replace"))
        if line == b"PING":
            self._send(b"PONG\r\n")
            return line
        if line.startswith(b"MSG "):
            parts = line.decode().split(" ")
            # MSG <subject> <sid> [reply-to] <#bytes>
            subject, sid = parts[1], int(parts[2])
            size = int(parts[-1])
            # the header is consumed: the payload MUST follow. A drain()
            # poll timeout firing mid-payload would desync the stream,
            # so the payload read gets its own generous window and a
            # stall is a hard protocol error, not a quiet return
            old_timeout = self.sock.gettimeout()
            if old_timeout is not None and old_timeout < 5.0:
                self.sock.settimeout(5.0)
            try:
                payload = self._reader.read_exact(size)
                self._reader.read_exact(2)  # trailing \r\n
            except (TimeoutError, socket.timeout) as exc:
                raise NatsError(
                    f"MSG payload stalled mid-frame ({size} bytes)"
                ) from exc
            finally:
                self.sock.settimeout(old_timeout)
            self.inbox.append((subject, sid, payload))
        return line

    def publish(self, subject: str, payload: bytes) -> None:
        self._send(
            f"PUB {subject} {len(payload)}\r\n".encode()
            + payload
            + b"\r\n"
        )
        if self.verbose:
            self._await_ok()

    def _await_ok(self) -> None:
        while True:
            if self._handle_line(self._reader.read_line()) == b"+OK":
                return

    def subscribe(self, subject: str, sid: int = 1) -> None:
        self._send(f"SUB {subject} {sid}\r\n".encode())
        if self.verbose:
            self._await_ok()

    def unsubscribe(self, sid: int) -> None:
        self._send(f"UNSUB {sid}\r\n".encode())

    def drain(self, timeout: float = 0.05) -> list[tuple[str, int, bytes]]:
        """Pull whatever the server has delivered into the inbox and
        return it (non-blocking beyond ``timeout``)."""
        self.sock.settimeout(timeout)
        try:
            while True:
                self._handle_line(self._reader.read_line())
        except (TimeoutError, socket.timeout):
            pass
        finally:
            self.sock.settimeout(None)
        out, self.inbox = self.inbox, []
        return out

    def flush(self) -> None:
        """PING/PONG round trip: everything sent before it is processed."""
        self.sock.settimeout(10.0)
        try:
            self._send(b"PING\r\n")
            self._await_pong()
        finally:
            self.sock.settimeout(None)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class NatsTransport:
    """MessageTransport (engine/storage.py contract) over a live NATS
    connection: SUB for reads, PUB for writes, one subject per
    transport — the reference NATS connector's shape."""

    def __init__(
        self,
        host: str,
        port: int,
        subject: str,
        *,
        token: str | None = None,
        user: str | None = None,
        password: str | None = None,
        subscribe: bool = True,
    ) -> None:
        self.subject = subject
        self.conn = NatsConnection(
            host, port, token=token, user=user, password=password
        )
        if subscribe:
            self.conn.subscribe(subject, sid=1)
            self.conn.flush()  # SUB registered before the first poll
        # write-only transports do NOT subscribe: the server would echo
        # every published message back to this connection, and with
        # nobody draining, the TCP buffers eventually deadlock both ends
        self._offset = 0

    def produce(self, value: Any, key: Any = None) -> None:
        payload = value if isinstance(value, bytes) else str(value).encode()
        self.conn.publish(self.subject, payload)

    def poll_messages(self) -> list[Message]:
        out = []
        for subject, _sid, payload in self.conn.drain():
            try:
                value: Any = payload.decode("utf-8")
            except UnicodeDecodeError:
                value = payload
            out.append(
                Message(
                    value,
                    key=None,
                    topic=subject,
                    partition=0,
                    offset=self._offset,
                )
            )
            self._offset += 1
        return out

    def finished(self) -> bool:
        return False  # a NATS subject is an endless stream

    def close(self) -> None:
        self.conn.close()


# -- fake server -------------------------------------------------------------


class FakeNatsServer:
    """In-process NATS server: real INFO/CONNECT/PING/PUB/SUB/MSG frames,
    subject routing with wildcards, optional token auth."""

    def __init__(self, *, token: str | None = None) -> None:
        self.token = token
        #: every (client_id, verb) frame the server parsed, in order
        self.frames: list[tuple[int, str]] = []
        #: all published payloads by subject (independent of routing)
        self.published: dict[str, list[bytes]] = {}
        self._lock = threading.Lock()
        #: sid registry: (conn, sid, pattern)
        self._subs: list[tuple[Any, int, str]] = []
        #: conn id -> serialized send fn (one writer lock per connection)
        self._sends: dict[int, Any] = {}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._closing = False
        self._next_client = [0]
        threading.Thread(target=self._serve, daemon=True).start()

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _serve(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            with self._lock:
                self._next_client[0] += 1
                cid = self._next_client[0]
            threading.Thread(
                target=self._handle, args=(conn, cid), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, cid: int) -> None:
        try:
            self._session(conn, cid)
        except (NatsError, OSError, ValueError):
            pass  # disconnects mid-frame are a normal client exit
        finally:
            with self._lock:
                self._subs = [s for s in self._subs if s[0] is not conn]
            conn.close()

    def _session(self, conn: socket.socket, cid: int) -> None:
        info = {
            "server_id": "fake-nats",
            "version": "2.10.0-fake",
            "proto": 1,
            "max_payload": 1 << 20,
            "auth_required": self.token is not None,
        }
        send_lock = threading.Lock()

        def send(data: bytes) -> None:
            with send_lock:
                # pwc-ok: PWC403 — the lock serializes this socket's writers
                conn.sendall(data)

        with self._lock:
            self._sends[id(conn)] = send
        send(f"INFO {json.dumps(info)}\r\n".encode())
        reader = _LineReader(conn)
        authed = self.token is None
        verbose = False
        while True:
            line = reader.read_line()
            verb = line.split(b" ", 1)[0].decode("ascii", "replace")
            with self._lock:
                self.frames.append((cid, verb))
            if verb == "CONNECT":
                options = json.loads(line[8:])
                verbose = bool(options.get("verbose"))
                if self.token is not None:
                    authed = options.get("auth_token") == self.token
                if verbose and authed:
                    send(b"+OK\r\n")
            elif verb == "PING":
                if not authed:
                    send(b"-ERR 'Authorization Violation'\r\n")
                    return
                send(b"PONG\r\n")
            elif verb == "PONG":
                pass
            elif verb == "SUB":
                if not authed:
                    send(b"-ERR 'Authorization Violation'\r\n")
                    return
                parts = line.decode().split(" ")
                pattern, sid = parts[1], int(parts[-1])
                with self._lock:
                    self._subs.append((conn, sid, pattern))
                if verbose:
                    send(b"+OK\r\n")
            elif verb == "UNSUB":
                parts = line.decode().split(" ")
                sid = int(parts[1])
                with self._lock:
                    self._subs = [
                        s
                        for s in self._subs
                        if not (s[0] is conn and s[1] == sid)
                    ]
                if verbose:
                    send(b"+OK\r\n")
            elif verb == "PUB":
                parts = line.decode().split(" ")
                subject = parts[1]
                size = int(parts[-1])
                payload = reader.read_exact(size)
                reader.read_exact(2)  # \r\n
                if not authed:
                    send(b"-ERR 'Authorization Violation'\r\n")
                    return
                with self._lock:
                    self.published.setdefault(subject, []).append(payload)
                    subs = list(self._subs)
                    sends = dict(self._sends)
                for target, sid, pattern in subs:
                    if _subject_matches(pattern, subject):
                        frame = (
                            f"MSG {subject} {sid} {size}\r\n".encode()
                            + payload
                            + b"\r\n"
                        )
                        sends[id(target)](frame)
                if verbose:
                    send(b"+OK\r\n")
            else:
                send(b"-ERR 'Unknown Protocol Operation'\r\n")
