"""Filesystem connector: pw.io.fs.read / write
(reference: python/pathway/io/fs/__init__.py, 369 LoC)."""

from __future__ import annotations

import os
from typing import Any

from pathway_tpu.engine.connectors import (
    DsvFormatter,
    DsvParser,
    FileWriter,
    FsReader,
    IdentityParser,
    JsonLinesFormatter,
    JsonLinesParser,
)
from pathway_tpu.engine.graph import Node, Scope
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import converter_for, input_table


def read(
    path: str | os.PathLike,
    *,
    format: str = "csv",  # noqa: A002
    schema: schema_mod.SchemaMetaclass | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    csv_settings: Any = None,
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    if format in ("plaintext", "plaintext_by_file", "binary"):
        schema = schema_mod.schema_from_types(
            data=bytes if format == "binary" else str
        )
    if schema is None:
        raise ValueError("schema= is required for csv/json formats")
    column_names = schema.column_names()
    dtypes = schema.dtypes()
    binary = format == "binary"

    def make_reader():
        return FsReader(path, mode=mode, binary=binary)

    def make_parser(names):
        if format == "csv":
            delimiter = ","
            if csv_settings is not None:
                delimiter = getattr(csv_settings, "delimiter", ",")
            return DsvParser(
                names,
                converters=[converter_for(dtypes[n]) for n in names],
                delimiter=delimiter,
            )
        if format == "json":
            return JsonLinesParser(names)
        if format == "plaintext":
            return IdentityParser(split_lines=True)
        if format in ("plaintext_by_file", "binary"):
            return IdentityParser(binary=binary, split_lines=False)
        raise ValueError(f"unknown format {format!r}")

    return input_table(
        schema,
        make_reader,
        make_parser,
        source_name=f"fs:{path}",
        with_metadata=with_metadata,
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def write(table: Table, filename: str | os.PathLike, *, format: str = "json", **kwargs: Any) -> None:  # noqa: A002
    column_names = table.column_names()

    def attach(scope: Scope, node: Node):
        formatter = DsvFormatter() if format == "csv" else JsonLinesFormatter()
        writer = FileWriter(filename, formatter, column_names)
        scope.subscribe_table(
            node,
            on_change=writer.on_change,
            on_time_end=writer.on_time_end,
            on_end=writer.on_end,
        )
        return None

    G.add_sink(table, attach)
