"""pw.io.nats — NATS subject connector (reference:
python/pathway/io/nats/__init__.py, 277 LoC). Message-queue shaped: same
transport seam as kafka; default transport gated on nats-py."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io import kafka as _kafka
from pathway_tpu.io._utils import require


def read(
    uri: str | None = None,
    topic: str | None = None,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "json",  # noqa: A002
    transport: Any = None,
    **kwargs: Any,
) -> Table:
    if transport is None:
        require("nats", "pw.io.nats")
        raise NotImplementedError("nats transport wiring requires a live server")
    return _kafka.read(
        None, topic, schema=schema, format=format, transport=transport, **kwargs
    )


def write(
    table: Table,
    uri: str | None = None,
    topic: str | None = None,
    *,
    transport: Any = None,
    **kwargs: Any,
) -> None:
    if transport is None:
        require("nats", "pw.io.nats")
        raise NotImplementedError("nats transport wiring requires a live server")
    _kafka.write(table, None, topic, transport=transport, **kwargs)
