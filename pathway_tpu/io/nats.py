"""pw.io.nats — NATS subject connector (reference:
python/pathway/io/nats/__init__.py, 277 LoC; NATS reader/writer in
src/connectors/data_storage.rs). Message-queue shaped: same engine seam
as kafka, with the wire-protocol client in ``io/_nats_wire.py``
(INFO/CONNECT handshake, PUB/SUB/MSG frames, token/user auth) as the
default transport — an injected ``transport=`` overrides it."""

from __future__ import annotations

from typing import Any
from urllib.parse import urlparse

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io import kafka as _kafka


def _wire_transport(
    uri: str | None, topic: str | None, subscribe: bool = True
) -> Any:
    from pathway_tpu.io._nats_wire import NatsTransport

    if uri is None or topic is None:
        raise ValueError("pw.io.nats needs uri and topic")
    parsed = urlparse(uri if "://" in uri else f"nats://{uri}")
    user = parsed.username or None
    password = parsed.password or None
    token = None
    if user is not None and password is None:
        # nats://<token>@host — bare userinfo is a token (nats.io URLs)
        token, user = user, None
    return NatsTransport(
        parsed.hostname or "127.0.0.1",
        parsed.port or 4222,
        topic,
        token=token,
        user=user,
        password=password,
        subscribe=subscribe,
    )


def read(
    uri: str | None = None,
    topic: str | None = None,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "json",  # noqa: A002
    transport: Any = None,
    **kwargs: Any,
) -> Table:
    """Read a NATS subject (reference nats.read): SUB over the wire
    client; ``uri`` accepts ``nats://[user:pass@]host:port``."""
    if transport is None:
        transport = _wire_transport(uri, topic)
    return _kafka.read(
        None, topic, schema=schema, format=format, transport=transport, **kwargs
    )


def write(
    table: Table,
    uri: str | None = None,
    topic: str | None = None,
    *,
    transport: Any = None,
    **kwargs: Any,
) -> None:
    """Publish a table's update stream to a NATS subject (reference
    nats.write): PUB frames over the wire client."""
    if transport is None:
        transport = _wire_transport(uri, topic, subscribe=False)
    _kafka.write(table, None, topic, transport=transport, **kwargs)
