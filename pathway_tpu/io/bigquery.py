"""pw.io.bigquery — write via the streaming insert API (reference:
python/pathway/io/bigquery/__init__.py).

The REST protocol itself is implemented here
(:class:`RestBigQueryClient`: ``tabledata.insertAll`` requests with
``insertId`` deduplication ids), reachable through ``api_base=`` +
``access_token=`` or a custom ``http_fn``; tests round-trip against an
in-process HTTP fake speaking the same endpoint. The
``insert_rows_json`` client seam remains for google-cloud-bigquery."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.formats import DocumentFormatter
from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require

BIGQUERY_API = "https://bigquery.googleapis.com/bigquery/v2"


class RestBigQueryClient:
    """Speaks the BigQuery ``tabledata.insertAll`` REST endpoint:
    ``POST {base}/projects/{p}/datasets/{d}/tables/{t}/insertAll`` with
    per-row ``insertId`` deduplication ids."""

    def __init__(
        self,
        project_id: str,
        api_base: str = BIGQUERY_API,
        access_token: str | None = None,
        http_fn: Callable[[str, dict], dict] | None = None,
    ) -> None:
        self.project_id = project_id
        self.api_base = api_base.rstrip("/")
        if http_fn is None:
            from pathway_tpu.io._utils import post_json

            def http_fn(url: str, payload: dict) -> dict:
                return post_json(url, payload, token=access_token)

        self.http_fn = http_fn
        # insertIds are BigQuery's best-effort dedup handle and must be
        # globally unique: a restarted process reusing a counter would
        # have its first rows silently swallowed as "duplicates"
        import uuid

        self._run_id = uuid.uuid4().hex
        self._seq = 0

    def insert_rows_json(self, table_id: str, rows: list[dict]) -> None:
        dataset, _, table = table_id.partition(".")
        url = (
            f"{self.api_base}/projects/{self.project_id}/datasets/"
            f"{dataset}/tables/{table}/insertAll"
        )
        payload_rows = []
        for row in rows:
            self._seq += 1
            payload_rows.append(
                {"insertId": f"pw-{self._run_id}-{self._seq}", "json": row}
            )
        body = self.http_fn(
            url,
            {
                "kind": "bigquery#tableDataInsertAllRequest",
                "rows": payload_rows,
            },
        )
        errors = body.get("insertErrors")
        if errors:
            raise RuntimeError(f"bigquery insert errors: {errors}")


class _BigQueryWriter:
    def __init__(self, client: Any, table_id: str, formatter: DocumentFormatter):
        self.client = client
        self.table_id = table_id
        self.formatter = formatter
        self._batch: list[dict] = []

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        self._batch.append(self.formatter.format(key, values, time, diff))

    def on_time_end(self, time: int) -> None:
        if self._batch:
            self.client.insert_rows_json(self.table_id, self._batch)
            self._batch = []

    def on_end(self) -> None:
        self.on_time_end(-1)


def write(
    table: Table,
    dataset_name: str | None = None,
    table_name: str | None = None,
    service_user_credentials_file: str | None = None,
    *,
    client: Any = None,
    project_id: str | None = None,
    access_token: str | None = None,
    api_base: str = BIGQUERY_API,
    **kwargs: Any,
) -> None:
    """Stream the table's update log into BigQuery. Client resolution:
    explicit ``client=`` seam; else the built-in REST client when
    ``project_id=`` is given (with ``access_token=``/``api_base=``);
    else google-cloud-bigquery from the credentials file."""
    if client is None and project_id is not None:
        client = RestBigQueryClient(
            project_id, api_base=api_base, access_token=access_token
        )
    if client is None:
        bq = require("google.cloud.bigquery", "pw.io.bigquery")
        creds_client = bq.Client.from_service_account_json(
            service_user_credentials_file
        )

        class _Adapter:
            def insert_rows_json(self, table_id: str, rows: list) -> None:
                errors = creds_client.insert_rows_json(table_id, rows)
                if errors:
                    raise RuntimeError(f"bigquery insert errors: {errors}")

        client = _Adapter()
    table_id = f"{dataset_name}.{table_name}"

    def make_writer(column_names):
        return _BigQueryWriter(client, table_id, DocumentFormatter(column_names))

    attach_writer(table, make_writer)
