"""pw.io.bigquery — write via the streaming insert API (reference:
python/pathway/io/bigquery/__init__.py). Client seam:
``insert_rows_json(table_id, [rows])``; google-cloud-bigquery adapts
directly, tests inject a recorder."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import DocumentFormatter
from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require


class _BigQueryWriter:
    def __init__(self, client: Any, table_id: str, formatter: DocumentFormatter):
        self.client = client
        self.table_id = table_id
        self.formatter = formatter
        self._batch: list[dict] = []

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        self._batch.append(self.formatter.format(key, values, time, diff))

    def on_time_end(self, time: int) -> None:
        if self._batch:
            self.client.insert_rows_json(self.table_id, self._batch)
            self._batch = []

    def on_end(self) -> None:
        self.on_time_end(-1)


def write(
    table: Table,
    dataset_name: str | None = None,
    table_name: str | None = None,
    service_user_credentials_file: str | None = None,
    *,
    client: Any = None,
    **kwargs: Any,
) -> None:
    if client is None:
        bq = require("google.cloud.bigquery", "pw.io.bigquery")
        creds_client = bq.Client.from_service_account_json(
            service_user_credentials_file
        )

        class _Adapter:
            def insert_rows_json(self, table_id: str, rows: list) -> None:
                errors = creds_client.insert_rows_json(table_id, rows)
                if errors:
                    raise RuntimeError(f"bigquery insert errors: {errors}")

        client = _Adapter()
    table_id = f"{dataset_name}.{table_name}"

    def make_writer(column_names):
        return _BigQueryWriter(client, table_id, DocumentFormatter(column_names))

    attach_writer(table, make_writer)
