"""pw.io.null — consume a table without writing anywhere
(reference: python/pathway/io/null/__init__.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.graph import Node, Scope
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def write(table: Table, **kwargs: Any) -> None:
    def attach(scope: Scope, node: Node):
        scope.subscribe_table(node)
        return None

    G.add_sink(table, attach)
