"""pw.io.minio — MinIO speaks the S3 protocol (reference:
python/pathway/io/minio/__init__.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import s3 as _s3


class MinIOSettings:
    def __init__(
        self,
        endpoint: str,
        bucket_name: str,
        access_key: str,
        secret_access_key: str,
        *,
        with_path_style: bool = True,
    ) -> None:
        self.settings = _s3.AwsS3Settings(
            bucket_name=bucket_name,
            access_key=access_key,
            secret_access_key=secret_access_key,
            endpoint=endpoint,
            with_path_style=with_path_style,
        )


def read(path: str, minio_settings: MinIOSettings | None = None, **kwargs: Any):
    settings = minio_settings.settings if minio_settings else None
    return _s3.read(path, aws_s3_settings=settings, **kwargs)


def write(table, path: str, minio_settings: MinIOSettings | None = None, **kwargs: Any):
    settings = minio_settings.settings if minio_settings else None
    return _s3.write(table, path, aws_s3_settings=settings, **kwargs)
