"""Input synchronization groups: sources advance together.

Reference: connector synchronization groups (SURVEY §2.2 —
``connector_group`` registration in src/connectors/mod.rs +
ConnectorGroupDescriptor in python_api.rs): sources registered in one
group hold back rows whose designated time column runs more than
``max_difference`` ahead of the slowest source, so joins over multiple
live streams see aligned time ranges instead of whichever source happens
to read faster.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference


class InputSynchronizationGroup:
    """Shared pacing state for a set of input drivers."""

    def __init__(self, max_difference: Any) -> None:
        self.max_difference = max_difference
        self._members: list = []
        self._done: set[int] = set()
        self._owner: int | None = None  # current run's GraphRunner id
        #: driver -> time of its next *held* event (None = no backlog)
        self.pending_head: dict[int, Any] = {}
        #: driver -> max admitted time
        self.admitted: dict[int, Any] = {}

    def ensure_run(self, owner: int) -> None:
        """Membership is per run: a rebuild (retry after a failed run,
        repeated capture) starts from a clean slate instead of being
        blocked by stale drivers."""
        if self._owner != owner:
            self._owner = owner
            self._members = []
            self._done = set()
            self.pending_head = {}
            self.admitted = {}

    def register(self, driver: Any) -> None:
        self._members.append(driver)
        self.pending_head[id(driver)] = None
        self.admitted[id(driver)] = None

    def _frontier(self, member: Any) -> Any:
        """A member's frontier: its next waiting event, else its last
        admitted time (a source with no backlog doesn't hold others back
        once it has caught up)."""
        head = self.pending_head[id(member)]
        if head is not None:
            return head
        return self.admitted[id(member)]

    def mark_done(self, driver: Any) -> None:
        """A finished source stops capping the others."""
        self._done.add(id(driver))

    def admit(self, driver: Any, t: Any) -> bool:
        """May ``driver`` emit an event at time ``t`` now? Allowed while
        ``t <= min(other frontiers) + max_difference``; a member that has
        produced nothing yet blocks everyone (all sources start aligned)."""
        for member in self._members:
            if member is driver or id(member) in self._done:
                continue
            frontier = self._frontier(member)
            if frontier is None:
                return False  # member hasn't produced anything yet
            try:
                if t > frontier + self.max_difference:
                    return False
            except TypeError:
                # non-comparable mix: fail OPEN — denying forever would
                # deadlock the run on a single malformed row
                continue
        prev = self.admitted[id(driver)]
        try:
            newer = prev is None or t > prev
        except TypeError:
            newer = True
        if newer:
            self.admitted[id(driver)] = t
        return True

    def note_pending(self, driver: Any, t: Any | None) -> None:
        self.pending_head[id(driver)] = t


def register_input_synchronization_group(
    *columns: ColumnReference, max_difference: Any
) -> InputSynchronizationGroup:
    """Each column designates (input table, time column); the tables'
    connectors then advance in lockstep within ``max_difference``."""
    if len(columns) < 2:
        raise ValueError("a synchronization group needs at least two sources")
    group = InputSynchronizationGroup(max_difference)
    for ref in columns:
        if not isinstance(ref, ColumnReference):
            raise TypeError("pass column references (table.time_column)")
        table = ref.table
        spec = table._spec
        if spec.kind != "input":
            raise ValueError(
                f"synchronization groups apply to connector input tables; "
                f"{table._name} is {spec.kind!r}"
            )
        spec.params["sync_group"] = group
        spec.params["sync_column"] = ref.name
    return group
