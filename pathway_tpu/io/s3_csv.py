"""pw.io.s3_csv (reference: python/pathway/io/s3_csv/__init__.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import s3 as _s3


def read(path: str, **kwargs: Any):
    kwargs.setdefault("format", "csv")
    return _s3.read(path, **kwargs)
