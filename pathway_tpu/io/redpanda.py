"""pw.io.redpanda — Redpanda speaks the Kafka protocol; this module is the
kafka connector under the compatible name (reference:
python/pathway/io/redpanda/__init__.py, 294 LoC of re-exports)."""

from pathway_tpu.io.kafka import read, simple_read, write  # noqa: F401

__all__ = ["read", "simple_read", "write"]
