"""pw.io.http — REST ingress: webserver + request/response connector pair.

Reference: python/pathway/io/http/_server.py — PathwayWebserver (aiohttp,
:329) and rest_connector (:624): each HTTP request becomes a row in a query
table; a response writer subscribed to the result table resolves the pending
HTTP future when the row's answer is produced. This is the serving path of
VectorStoreServer / the RAG QA servers (SURVEY.md §3.5).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import threading
import uuid
from typing import Any, Callable, Sequence

from pathway_tpu.engine.connectors import INSERT, DELETE, ParsedEvent, QueueReader
from pathway_tpu.engine.value import Json, Pointer, ref_scalar
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table

_REQUEST_ID = "_pw_request_id"


#: pw dtype -> OpenAPI property schema. Matched against the dtype repr:
#: scalars print as uppercase names (INT, STR, ...), composites as
#: capitalised constructors (List(INT), Tuple(...), Array(...), Pointer)
def _openapi_type(dtype: Any) -> dict:
    base = dtype.strip_optional() if hasattr(dtype, "strip_optional") else dtype
    name = repr(base)
    mapping = {
        "INT": {"type": "integer"},
        "FLOAT": {"type": "number", "format": "double"},
        "BOOL": {"type": "boolean"},
        "STR": {"type": "string"},
        "BYTES": {"type": "string", "format": "byte"},
        "DATE_TIME_NAIVE": {"type": "string", "format": "date-time"},
        "DATE_TIME_UTC": {"type": "string", "format": "date-time"},
        "DURATION": {"type": "string"},
        "JSON": {},  # free-form
    }
    for key, spec in mapping.items():
        if name.startswith(key):
            return dict(spec)
    if name.startswith(("List", "Tuple", "Array")):
        return {"type": "array"}
    if name.startswith("Pointer"):
        return {"type": "string"}
    return {}


class PathwayWebserver:
    """One aiohttp server shared by any number of rest_connector routes.

    ``with_schema_endpoint`` serves an OpenAPI 3.0.3 description of every
    registered route at ``/_schema`` (``?format=json`` or the default
    yaml), generated from each route's pw schema — mirroring the
    reference webserver's schema endpoint
    (python/pathway/io/http/_server.py:329). ``with_cors`` answers
    preflight ``OPTIONS`` and stamps ``Access-Control-Allow-*`` headers
    on every response (no external CORS dependency)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        with_schema_endpoint: bool = True,
        with_cors: bool = False,
    ) -> None:
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._routes: dict[str, Callable] = {}
        self._started = False
        self._loop: asyncio.AbstractEventLoop | None = None
        self._ready = threading.Event()
        self._openapi: dict[str, Any] = {
            "openapi": "3.0.3",
            "info": {
                "title": "pathway_tpu generated openapi description",
                "version": "1.0.0",
            },
            "paths": {},
            "servers": [{"url": f"http://{host}:{port}/"}],
        }
        if with_schema_endpoint:
            self._routes["/_schema"] = self._schema_handler

    def add_route(
        self,
        route: str,
        handler: Callable,
        schema: Any | None = None,
        methods: Sequence[str] = ("GET", "POST"),
    ) -> None:
        if self._started:
            raise RuntimeError("cannot add routes after the server started")
        self._routes[route] = handler
        if schema is not None:
            self._openapi["paths"][route] = self._route_docs(schema, methods)

    def _route_docs(self, schema: Any, methods: Sequence[str]) -> dict:
        columns = schema.column_names()
        dtypes = dict(schema.dtypes())
        required = [
            n for n in columns if not getattr(dtypes[n], "is_optional", lambda: False)()
        ]
        properties = {n: _openapi_type(dtypes[n]) for n in columns}
        docs: dict[str, Any] = {}
        if "POST" in methods:
            docs["post"] = {
                "requestBody": {
                    "content": {
                        "application/json": {
                            "schema": {
                                "type": "object",
                                "properties": properties,
                                "required": required,
                            }
                        }
                    },
                    "required": True,
                },
                "responses": {"200": {"description": "OK"}},
            }
        if "GET" in methods:
            docs["get"] = {
                "parameters": [
                    {
                        "name": n,
                        "in": "query",
                        "required": n in required,
                        "schema": properties[n] or {"type": "string"},
                    }
                    for n in columns
                ],
                "responses": {"200": {"description": "OK"}},
            }
        return docs

    def openapi_description_json(self, origin: str | None = None) -> dict:
        import copy

        desc = copy.deepcopy(self._openapi)
        if origin:
            desc["servers"] = [{"url": origin}]
        return desc

    async def _schema_handler(self, request: Any):
        from aiohttp import web

        origin = f"{request.scheme}://{request.host}"
        fmt = request.query.get("format", "yaml")
        desc = self.openapi_description_json(origin)
        if fmt == "json":
            return web.json_response(desc)
        if fmt != "yaml":
            return web.json_response(
                {"error": f"unknown format {fmt!r}; use 'json' or 'yaml'"},
                status=400,
            )
        import yaml

        return web.Response(
            text=yaml.safe_dump(desc, sort_keys=False),
            content_type="text/x-yaml",
        )

    _CORS_HEADERS = {
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Headers": "*",
        "Access-Control-Allow-Methods": "GET, POST, OPTIONS",
        "Access-Control-Expose-Headers": "*",
    }

    def start(self) -> None:
        if self._started:
            return
        self._started = True

        def serve() -> None:
            from aiohttp import web

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            middlewares = []
            if self.with_cors:
                cors_headers = self._CORS_HEADERS

                @web.middleware
                async def cors_middleware(request, handler):
                    if request.method == "OPTIONS":
                        return web.Response(headers=cors_headers)
                    resp = await handler(request)
                    resp.headers.update(cors_headers)
                    return resp

                middlewares.append(cors_middleware)
            app = web.Application(middlewares=middlewares)
            for route, handler in self._routes.items():
                app.router.add_post(route, handler)
                app.router.add_get(route, handler)
                if self.with_cors:
                    app.router.add_route("OPTIONS", route, handler)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._ready.set()
            loop.run_forever()

        self._thread = threading.Thread(
            target=serve, name="pw-webserver", daemon=True
        )
        self._thread.start()
        self._ready.wait(timeout=10)


class RestResponseWriter:
    """Resolves pending HTTP futures from the result table's update stream."""

    def __init__(self, futures: dict[Pointer, concurrent.futures.Future]):
        self._futures = futures

    def attach(self, result_table: Table, runner: Any) -> None:
        node = runner.build(result_table)

        def on_change(key: Pointer, row: tuple, time: int, diff: int) -> None:
            if diff <= 0:
                return
            fut = self._futures.pop(key, None)
            if fut is not None and not fut.done():
                names = result_table.column_names()
                fut.set_result({n: v for n, v in zip(names, row)})

        runner.scope.subscribe_table(node, on_change=on_change)


def rest_connector(
    host: str = "127.0.0.1",
    port: int = 8080,
    *,
    schema: schema_mod.SchemaMetaclass,
    route: str = "/",
    webserver: PathwayWebserver | None = None,
    delete_completed_queries: bool = True,
    request_timeout: float = 30.0,
) -> tuple[Table, Callable[[Table, Any], None]]:
    """Returns ``(query_table, attach_response)``.

    ``attach_response(result_table, runner)`` must be called (directly or via
    ``pw.io.http.PathwayRestServer``) before the streaming run starts; the
    result table must be keyed by the query table's ids.
    """
    server = webserver or PathwayWebserver(host, port)
    reader = QueueReader()
    futures: dict[Pointer, concurrent.futures.Future] = {}
    columns = schema.column_names()
    dtypes = dict(schema.dtypes())

    class _RestParser:
        def parse(self, payload: Any) -> list[ParsedEvent]:
            kind, rid, data = payload
            values = [rid]
            for name in columns:
                v = data.get(name)
                if dtypes[name].strip_optional() == dt.JSON and v is not None:
                    v = Json(v)
                values.append(v)
            return [ParsedEvent(kind, tuple(values))]

    full_schema = schema_mod.schema_from_dict(
        {
            _REQUEST_ID: {"dtype": dt.STR, "primary_key": True},
            **{n: dtypes[n] for n in columns},
        },
        name="RestRequestSchema",
    )

    async def handler(request: Any):
        from aiohttp import web

        try:
            if request.method == "GET":
                data = dict(request.query)
            else:
                data = await request.json()
        except (json.JSONDecodeError, ValueError):
            return web.json_response({"error": "invalid json"}, status=400)
        if not isinstance(data, dict):
            return web.json_response(
                {"error": "request body must be a JSON object"}, status=400
            )
        rid = uuid.uuid4().hex
        key = ref_scalar(rid)
        fut: concurrent.futures.Future = concurrent.futures.Future()
        futures[key] = fut
        reader.push(("insert", rid, data), source_id=rid)
        try:
            result = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=request_timeout
            )
        except asyncio.TimeoutError:
            futures.pop(key, None)
            return web.json_response({"error": "timeout"}, status=504)
        finally:
            if delete_completed_queries:
                reader.push(("delete", rid, data), source_id=rid)
        if isinstance(result, dict) and set(result) == {"result"}:
            result = result["result"]
        return web.json_response(_jsonable(result))

    server.add_route(route, handler, schema=schema)

    table = input_table(
        full_schema,
        make_reader=lambda: reader,
        make_parser=lambda _cols: _RestParser(),
        source_name=f"rest:{route}",
    )
    # start the webserver lazily at attach time so the port opens only when
    # a graph is actually run
    writer = RestResponseWriter(futures)

    def attach_response(result_table: Table, runner: Any) -> None:
        writer.attach(result_table, runner)
        server.start()

    return table, attach_response


def _jsonable(value: Any) -> Any:
    import numpy as np

    if isinstance(value, Json):
        return value.value
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.floating, np.integer)):
        return value.item()
    if isinstance(value, Pointer):
        return str(value)
    return value


# -- generic HTTP reader / writer --------------------------------------------


class RetryPolicy:
    """Retry delays for the HTTP writer (reference io/http RetryPolicy)."""

    def __init__(self, first_delay_ms: int = 1000, backoff_factor: float = 2.0):
        self.first_delay_ms = first_delay_ms
        self.backoff_factor = backoff_factor

    @classmethod
    def default(cls) -> "RetryPolicy":
        return cls()


class _HttpWriter:
    """POST one flat-JSON object (row + time + diff) per change (reference
    io/http/__init__.py:158 write). ``request_fn(url, payload_dict)`` is
    injectable; the default uses `requests`."""

    def __init__(
        self,
        endpoint: str,
        column_names: Sequence[str],
        request_fn: Callable[[str, dict], Any] | None,
        n_retries: int,
        retry_policy: RetryPolicy,
    ) -> None:
        self.endpoint = endpoint
        self.column_names = list(column_names)
        if request_fn is None:
            import requests

            request_fn = lambda url, payload: requests.post(  # noqa: E731
                url, json=payload, timeout=30
            ).raise_for_status()
        self.request_fn = request_fn
        self.n_retries = n_retries
        self.retry_policy = retry_policy

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        import time as _time

        payload = {}
        for name, v in zip(self.column_names, values):
            payload[name] = v.value if isinstance(v, Json) else v
        payload["time"] = time
        payload["diff"] = diff
        delay = self.retry_policy.first_delay_ms / 1000.0
        for attempt in range(self.n_retries + 1):
            try:
                self.request_fn(self.endpoint, payload)
                return
            except Exception:
                if attempt == self.n_retries:
                    raise
                _time.sleep(delay)
                delay *= self.retry_policy.backoff_factor

    def on_time_end(self, time: int) -> None:
        pass

    def on_end(self) -> None:
        pass


def write(
    table: Table,
    url: str,
    *,
    n_retries: int = 0,
    retry_policy: RetryPolicy | None = None,
    request_fn: Callable[[str, dict], Any] | None = None,
    **kwargs: Any,
) -> None:
    from pathway_tpu.io._utils import attach_writer

    policy = retry_policy or RetryPolicy.default()

    def make_writer(column_names):
        return _HttpWriter(url, column_names, request_fn, n_retries, policy)

    attach_writer(table, make_writer)


def read(
    url: str,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "json",  # noqa: A002
    poll_interval_ms: int = 1000,
    request_fn: Callable[[str], Any] | None = None,
    n_retries: int = 0,
    **kwargs: Any,
) -> Table:
    """Poll ``url`` and parse each response body as JSON lines / plaintext
    (reference io/http/__init__.py read: polling streaming reader).
    ``request_fn(url) -> str`` is injectable for offline use."""
    import time as _time

    from pathway_tpu.engine.connectors import JsonLinesParser, IdentityParser, Reader

    if request_fn is None:
        def request_fn(u):  # pragma: no cover - needs network
            import requests

            resp = requests.get(u, timeout=30)
            resp.raise_for_status()
            return resp.text

    if format == "plaintext":
        schema = schema_mod.schema_from_types(data=str)
    if schema is None:
        raise ValueError("schema= is required for json format")

    class _HttpPollReader(Reader):
        # a re-poll returning the same body is a re-read of the same
        # source: rows replace the previous poll's instead of accumulating
        replaces_sources = True

        def __init__(self) -> None:
            self._last_poll = 0.0
            self._polled_once = False
            self._last_body: str | None = None

        def poll(self):
            now = _time.monotonic()
            if (
                now - self._last_poll < poll_interval_ms / 1000.0
                and self._polled_once
            ):
                return [], False
            self._last_poll = now
            self._polled_once = True
            delay = 0.5
            for attempt in range(n_retries + 1):
                try:
                    body = request_fn(url)
                    break
                except Exception:
                    if attempt == n_retries:
                        raise
                    _time.sleep(delay)
                    delay *= 2
            if not body or body == self._last_body:
                return [], False
            self._last_body = body
            return [(body, url, {})], False

    make_parser = (
        (lambda names: JsonLinesParser(names))
        if format == "json"
        else (lambda names: IdentityParser(split_lines=True))
    )
    return input_table(
        schema, _HttpPollReader, make_parser, source_name=f"http:{url}"
    )
