"""pw.io.postgres — write update streams / snapshots into Postgres
(reference: python/pathway/io/postgres/__init__.py; PsqlWriter
src/connectors/data_storage.rs:1061, Psql formatters data_format.rs:1625,
:1684).

The database is reached through the built-in wire-protocol client
(``io/_pg_wire.py``: startup handshake with cleartext/md5/SCRAM-SHA-256
auth and sslmode-driven TLS, extended-query Parse/Bind/Execute/Sync with
$N placeholders, BEGIN/COMMIT transactional batches). An injected
``connection`` object with ``execute(statement, params)`` (and
optionally ``commit()``) overrides it; wrap a psycopg2 connection with
:func:`psycopg2_adapter` to translate the $N placeholders it cannot
execute natively.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import PsqlSnapshotFormatter, PsqlUpdatesFormatter
from pathway_tpu.engine.storage import PsqlWriter
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer


def psycopg2_adapter(conn: Any) -> Any:
    """Wrap a psycopg2 connection into the executor contract: the Psql
    formatters emit $N placeholders (which repeat — the snapshot upsert
    reuses $1 in VALUES, SET and WHERE), translated here to psycopg2's
    NAMED pyformat so each occurrence binds the same parameter."""
    import re

    class _Adapter:
        def execute(self, statement: str, params):
            stmt = re.sub(r"\$(\d+)", r"%(p\1)s", statement)
            named = {f"p{i + 1}": v for i, v in enumerate(params)}
            with conn.cursor() as cur:
                cur.execute(stmt, named)

        def commit(self):
            conn.commit()

    return _Adapter()


def _executor(postgres_settings: dict | None, connection: Any) -> Any:
    if connection is not None:
        return connection
    from pathway_tpu.io._pg_wire import PgWireConnection

    settings = dict(postgres_settings or {})
    return PgWireConnection(
        host=settings.get("host", "127.0.0.1"),
        port=int(settings.get("port", 5432)),
        user=settings.get("user", "pathway"),
        password=settings.get("password"),
        dbname=settings.get("dbname", settings.get("database", "pathway")),
        connect_timeout=float(settings.get("connect_timeout", 10.0)),
        sslmode=settings.get("sslmode", "prefer"),
    )


def write(
    table: Table,
    postgres_settings: dict | None = None,
    table_name: str | None = None,
    *,
    connection: Any = None,
    **kwargs: Any,
) -> None:
    """Append every change as a row (values..., time, diff) — the update-log
    shape (reference postgres.write)."""
    executor = _executor(postgres_settings, connection)

    def make_writer(column_names):
        return PsqlWriter(
            executor, PsqlUpdatesFormatter(table_name, column_names)
        )

    attach_writer(table, make_writer)


def write_snapshot(
    table: Table,
    postgres_settings: dict | None = None,
    table_name: str | None = None,
    primary_key: list[str] | None = None,
    *,
    connection: Any = None,
    **kwargs: Any,
) -> None:
    """Maintain ``table_name`` as the current snapshot: upsert on insert,
    DELETE on retraction (reference postgres.write_snapshot :113)."""
    if not primary_key:
        raise ValueError("write_snapshot needs primary_key=[...]")
    executor = _executor(postgres_settings, connection)

    def make_writer(column_names):
        return PsqlWriter(
            executor,
            PsqlSnapshotFormatter(table_name, primary_key, column_names),
        )

    attach_writer(table, make_writer)
