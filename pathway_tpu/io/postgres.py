"""pw.io.postgres — write update streams / snapshots into Postgres
(reference: python/pathway/io/postgres/__init__.py; PsqlWriter
src/connectors/data_storage.rs:1061, Psql formatters data_format.rs:1625,
:1684).

The database is reached through an injected ``connection`` object with
``execute(statement, params)`` (and optionally ``commit()``). psycopg2's
cursor adapts directly (after $N -> %s placeholder translation); tests use
a recording executor.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import PsqlSnapshotFormatter, PsqlUpdatesFormatter
from pathway_tpu.engine.storage import PsqlWriter
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require


def _executor(postgres_settings: dict | None, connection: Any) -> Any:
    if connection is not None:
        return connection
    psycopg2 = require("psycopg2", "pw.io.postgres")
    conn = psycopg2.connect(
        **{k: v for k, v in (postgres_settings or {}).items()}
    )

    class _Adapter:
        def execute(self, statement: str, params):
            import re

            # $N placeholders repeat (snapshot upsert reuses $1 in VALUES,
            # SET and WHERE) — translate to psycopg2's *named* pyformat so
            # each occurrence binds the same parameter
            stmt = re.sub(r"\$(\d+)", r"%(p\1)s", statement)
            named = {f"p{i + 1}": v for i, v in enumerate(params)}
            with conn.cursor() as cur:
                cur.execute(stmt, named)

        def commit(self):
            conn.commit()

    return _Adapter()


def write(
    table: Table,
    postgres_settings: dict | None = None,
    table_name: str | None = None,
    *,
    connection: Any = None,
    **kwargs: Any,
) -> None:
    """Append every change as a row (values..., time, diff) — the update-log
    shape (reference postgres.write)."""
    executor = _executor(postgres_settings, connection)

    def make_writer(column_names):
        return PsqlWriter(
            executor, PsqlUpdatesFormatter(table_name, column_names)
        )

    attach_writer(table, make_writer)


def write_snapshot(
    table: Table,
    postgres_settings: dict | None = None,
    table_name: str | None = None,
    primary_key: list[str] | None = None,
    *,
    connection: Any = None,
    **kwargs: Any,
) -> None:
    """Maintain ``table_name`` as the current snapshot: upsert on insert,
    DELETE on retraction (reference postgres.write_snapshot :113)."""
    if not primary_key:
        raise ValueError("write_snapshot needs primary_key=[...]")
    executor = _executor(postgres_settings, connection)

    def make_writer(column_names):
        return PsqlWriter(
            executor,
            PsqlSnapshotFormatter(table_name, primary_key, column_names),
        )

    attach_writer(table, make_writer)
