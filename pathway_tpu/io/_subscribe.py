"""pw.io.subscribe (reference: python/pathway/io/_subscribe.py:13)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.graph import Node, Scope
from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def subscribe(
    table: Table,
    on_change: Callable[..., Any] | None = None,
    on_end: Callable[[], Any] | None = None,
    on_time_end: Callable[[int], Any] | None = None,
    *,
    skip_errors: bool = True,
    _internal: bool = False,
) -> None:
    """Call ``on_change(key, row: dict, time, is_addition)`` for every update."""
    column_names = table.column_names()

    def attach(scope: Scope, node: Node):
        def _on_change(key: Pointer, values: tuple, time: int, diff: int) -> None:
            if on_change is not None:
                row = dict(zip(column_names, values))
                on_change(key=key, row=row, time=time, is_addition=diff > 0)

        scope.subscribe_table(
            node,
            on_change=_on_change,
            on_time_end=on_time_end,
            on_end=on_end,
            skip_errors=skip_errors,
        )
        return None

    G.add_sink(table, attach, internal=_internal)
