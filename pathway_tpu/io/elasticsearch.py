"""pw.io.elasticsearch — index update streams into Elasticsearch
(reference: python/pathway/io/elasticsearch/__init__.py:52;
ElasticSearchWriter src/connectors/data_storage.rs:1317)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import DocumentFormatter
from pathway_tpu.engine.storage import ElasticsearchWriter
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer


class ElasticSearchAuth:
    """Auth config holder (reference ElasticSearchAuth: basic/bearer/apikey)."""

    def __init__(self, kind: str, **params: Any) -> None:
        self.kind = kind
        self.params = params

    @classmethod
    def basic(cls, username: str, password: str) -> "ElasticSearchAuth":
        return cls("basic", username=username, password=password)

    @classmethod
    def bearer(cls, token: str) -> "ElasticSearchAuth":
        return cls("bearer", token=token)

    @classmethod
    def apikey(cls, apikey_id: str, apikey: str) -> "ElasticSearchAuth":
        return cls("apikey", apikey_id=apikey_id, apikey=apikey)


def write(
    table: Table,
    host: str | None = None,
    auth: ElasticSearchAuth | None = None,
    index_name: str | None = None,
    *,
    client: Any = None,
    **kwargs: Any,
) -> None:
    """Index one document (row + time + diff) per change through the
    built-in HTTP ``_bulk`` client (``io/_es_wire.py``: NDJSON frames,
    one bulk request per commit, Basic/Bearer/ApiKey auth). An injected
    ``client`` with ``index(index_name, document)`` overrides it."""
    if client is None:
        from pathway_tpu.io._es_wire import (
            EsBulkClient,
            auth_header_apikey,
            auth_header_basic,
            auth_header_bearer,
        )

        if host is None:
            raise ValueError("pw.io.elasticsearch needs host (or client=)")
        auth_header = None
        if auth is not None:
            if auth.kind == "basic":
                auth_header = auth_header_basic(
                    auth.params["username"], auth.params["password"]
                )
            elif auth.kind == "bearer":
                auth_header = auth_header_bearer(auth.params["token"])
            elif auth.kind == "apikey":
                auth_header = auth_header_apikey(
                    auth.params["apikey_id"], auth.params["apikey"]
                )
            else:
                raise ValueError(f"unknown auth kind {auth.kind!r}")
        client = EsBulkClient(host, auth_header=auth_header)

    def make_writer(column_names):
        return ElasticsearchWriter(
            client, index_name, DocumentFormatter(column_names)
        )

    attach_writer(table, make_writer)
