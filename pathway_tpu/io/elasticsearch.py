"""pw.io.elasticsearch — index update streams into Elasticsearch
(reference: python/pathway/io/elasticsearch/__init__.py:52;
ElasticSearchWriter src/connectors/data_storage.rs:1317)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import DocumentFormatter
from pathway_tpu.engine.storage import ElasticsearchWriter
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require


class ElasticSearchAuth:
    """Auth config holder (reference ElasticSearchAuth: basic/bearer/apikey)."""

    def __init__(self, kind: str, **params: Any) -> None:
        self.kind = kind
        self.params = params

    @classmethod
    def basic(cls, username: str, password: str) -> "ElasticSearchAuth":
        return cls("basic", username=username, password=password)

    @classmethod
    def bearer(cls, token: str) -> "ElasticSearchAuth":
        return cls("bearer", token=token)

    @classmethod
    def apikey(cls, apikey_id: str, apikey: str) -> "ElasticSearchAuth":
        return cls("apikey", apikey_id=apikey_id, apikey=apikey)


def write(
    table: Table,
    host: str | None = None,
    auth: ElasticSearchAuth | None = None,
    index_name: str | None = None,
    *,
    client: Any = None,
    **kwargs: Any,
) -> None:
    """Index one document (row + time + diff) per change. ``client`` needs
    ``index(index_name, document)``; elasticsearch-py adapts directly."""
    if client is None:
        es_mod = require("elasticsearch", "pw.io.elasticsearch")
        es_kwargs: dict[str, Any] = {}
        if auth is not None:
            if auth.kind == "basic":
                es_kwargs["basic_auth"] = (
                    auth.params["username"],
                    auth.params["password"],
                )
            elif auth.kind == "bearer":
                es_kwargs["bearer_auth"] = auth.params["token"]
            elif auth.kind == "apikey":
                es_kwargs["api_key"] = (
                    auth.params["apikey_id"],
                    auth.params["apikey"],
                )
            else:
                raise ValueError(f"unknown auth kind {auth.kind!r}")
        es = es_mod.Elasticsearch(host, **es_kwargs)

        class _Adapter:
            def index(self, index_name: str, document: dict) -> None:
                es.index(index=index_name, document=document)

        client = _Adapter()

    def make_writer(column_names):
        return ElasticsearchWriter(
            client, index_name, DocumentFormatter(column_names)
        )

    attach_writer(table, make_writer)
