"""pw.io.kafka — Kafka connector speaking the real wire protocol
(reference: python/pathway/io/kafka/__init__.py; KafkaReader
src/connectors/data_storage.rs:673, KafkaWriter :1239).

``transport=None`` (the default) connects to ``bootstrap.servers`` with
the framework's own Kafka binary-protocol client
(:mod:`pathway_tpu.io._kafka_wire`: Metadata/Produce/Fetch/ListOffsets,
RecordBatch v2 with CRC32C) — no external Kafka library needed. Tests
round-trip against :class:`pathway_tpu.io._kafka_wire.FakeKafkaBroker`
over a real socket; an injectable transport (``MessageTransport``) and
:class:`InMemoryTransport` remain for offline demos.

Also provided, mirroring the reference module: Confluent-style schema
registry support (``format='avro'`` with the 0x00+schema-id framing,
:class:`SchemaRegistry`) and :func:`read_from_upstash` (Upstash Kafka
REST consume API).
"""

from __future__ import annotations

import json as _json
import struct as _struct
from typing import Any, Callable, Sequence

from pathway_tpu.engine.connectors import (
    INSERT,
    UPSERT,
    JsonLinesFormatter,
    Parser,
    ParsedEvent,
)
from pathway_tpu.engine.storage import (
    InMemoryTransport,
    MessageQueueReader,
    MessageQueueWriter,
)
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, input_table
from pathway_tpu.io._kafka_wire import (  # noqa: F401 — re-exported API
    FakeKafkaBroker,
    KafkaWireClient,
    KafkaWireTransport,
)

__all__ = [
    "read",
    "write",
    "simple_read",
    "read_from_upstash",
    "InMemoryTransport",
    "FakeKafkaBroker",
    "KafkaWireTransport",
    "SchemaRegistry",
]


class _KafkaJsonParser(Parser):
    """value bytes -> JSON object -> schema columns; keyed by primary key
    columns when given (upsert session, like the reference's Kafka+json
    upsert path), else plain inserts."""

    def __init__(
        self, column_names: Sequence[str], primary_key: Sequence[str] | None
    ) -> None:
        super().__init__(column_names)
        self.primary_key = list(primary_key) if primary_key else None
        self.session_type = "upsert" if self.primary_key else "native"

    def parse(self, payload: Any) -> list[ParsedEvent]:
        import json

        from pathway_tpu.engine.value import Json

        msg_key, value = payload
        if value is None:
            # compacted-topic tombstone: with a primary key it deletes the
            # row whose key matches the message key (JSON-decoded when
            # possible, raw string otherwise)
            if not self.primary_key or msg_key is None:
                return []
            if isinstance(msg_key, bytes):
                msg_key = msg_key.decode("utf-8")
            try:
                decoded = json.loads(msg_key)
            except (ValueError, TypeError):
                decoded = msg_key
            if isinstance(decoded, dict):
                key = tuple(decoded.get(k) for k in self.primary_key)
            elif len(self.primary_key) == 1:
                key = (decoded,)
            else:
                raise ValueError(
                    "tombstone key must be a JSON object for a composite "
                    "primary key"
                )
            return [ParsedEvent(UPSERT, None, key=key)]
        if isinstance(value, bytes):
            value = value.decode("utf-8")
        obj = json.loads(value)
        values = tuple(
            Json(v) if isinstance(v, (dict, list)) else v
            for v in (obj.get(name) for name in self.column_names)
        )
        if self.primary_key:
            key = tuple(obj.get(k) for k in self.primary_key)
            return [ParsedEvent(UPSERT, values, key=key)]
        return [ParsedEvent(INSERT, values)]


class _KafkaRawParser(Parser):
    """value bytes -> single `data` column (format='raw'/'plaintext')."""

    def __init__(self, binary: bool) -> None:
        super().__init__(["data"])
        self.binary = binary

    def parse(self, payload: Any) -> list[ParsedEvent]:
        _key, value = payload
        if value is None:
            return []
        if self.binary and isinstance(value, str):
            value = value.encode("utf-8")
        if not self.binary and isinstance(value, bytes):
            value = value.decode("utf-8")
        return [ParsedEvent(INSERT, (value,))]


def _default_transport(
    rdkafka_settings: dict, topic: Any, mode: str = "streaming"
) -> KafkaWireTransport:
    bootstrap = rdkafka_settings.get("bootstrap.servers")
    if not bootstrap:
        raise ValueError(
            "rdkafka_settings['bootstrap.servers'] is required when no "
            "transport= is given"
        )
    if isinstance(topic, (list, tuple)):
        if len(topic) != 1:
            raise ValueError(
                "the wire transport reads one topic per connector; create "
                "one read() per topic"
            )
        topic = topic[0]
    if topic is None:
        raise ValueError("topic is required")
    start = rdkafka_settings.get("auto.offset.reset", "earliest")
    return KafkaWireTransport(
        bootstrap.split(",")[0], topic, mode=mode, start=start
    )


# -- Confluent-style schema registry ------------------------------------------


class SchemaRegistry:
    """Minimal Confluent schema-registry client (wire format: magic 0x00 +
    int32 schema id + Avro body; reference kafka/__init__.py registry
    support). ``request_fn(method, url, payload|None) -> dict`` is
    injectable; the default uses urllib against ``url``."""

    def __init__(
        self,
        url: str,
        request_fn: Callable[[str, str, dict | None], dict] | None = None,
    ) -> None:
        self.url = url.rstrip("/")
        if request_fn is None:

            def request_fn(method: str, full_url: str, payload):
                if method == "POST":
                    from pathway_tpu.io._utils import post_json

                    return post_json(
                        full_url,
                        payload,
                        timeout=30.0,
                        content_type=(
                            "application/vnd.schemaregistry.v1+json"
                        ),
                    )
                import urllib.request

                req = urllib.request.Request(full_url, method=method)
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return _json.loads(resp.read().decode())

        self.request_fn = request_fn
        self._by_id: dict[int, Any] = {}

    def get_schema(self, schema_id: int) -> Any:
        got = self._by_id.get(schema_id)
        if got is None:
            body = self.request_fn(
                "GET", f"{self.url}/schemas/ids/{schema_id}", None
            )
            got = _json.loads(body["schema"])
            self._by_id[schema_id] = got
        return got

    def register(self, subject: str, schema: Any) -> int:
        body = self.request_fn(
            "POST",
            f"{self.url}/subjects/{subject}/versions",
            {"schema": _json.dumps(schema)},
        )
        schema_id = int(body["id"])
        self._by_id[schema_id] = schema
        return schema_id

    def decode_message(self, raw: bytes) -> Any:
        import io as _io

        from pathway_tpu.io import _avro

        if not raw or raw[0] != 0:
            raise ValueError(
                "not a schema-registry framed message (magic byte != 0)"
            )
        (schema_id,) = _struct.unpack(">i", raw[1:5])
        schema = self.get_schema(schema_id)
        return _avro.decode(_io.BytesIO(raw[5:]), schema)

    def encode_message(self, schema_id: int, value: Any) -> bytes:
        import io as _io

        from pathway_tpu.io import _avro

        out = _io.BytesIO()
        out.write(b"\x00")
        out.write(_struct.pack(">i", schema_id))
        _avro.encode(out, self.get_schema(schema_id), value)
        return out.getvalue()


_AVRO_TYPES = {
    "INT": "long",
    "FLOAT": "double",
    "BOOL": "boolean",
    "STR": "string",
    "BYTES": "bytes",
}


def _avro_schema_of(schema: schema_mod.SchemaMetaclass, name: str) -> dict:
    fields = []
    for col, dtype in dict(schema.dtypes()).items():
        base = dtype.strip_optional()
        avro_t: Any = _AVRO_TYPES.get(str(base), "string")
        if dtype.is_optional():
            avro_t = ["null", avro_t]
        fields.append({"name": col, "type": avro_t})
    return {"type": "record", "name": name, "fields": fields}


class _KafkaAvroParser(Parser):
    """Schema-registry framed Avro value -> schema columns (reference
    kafka avro format with registry decoding)."""

    def __init__(
        self,
        column_names: Sequence[str],
        primary_key: Sequence[str] | None,
        registry: SchemaRegistry,
    ) -> None:
        super().__init__(column_names)
        self.primary_key = list(primary_key) if primary_key else None
        self.session_type = "upsert" if self.primary_key else "native"
        self.registry = registry

    def parse(self, payload: Any) -> list[ParsedEvent]:
        msg_key, value = payload
        if value is None:
            # tombstone: decode the message key exactly like the JSON
            # parser so int / composite primary keys retract correctly
            if not self.primary_key or msg_key is None:
                return []
            if isinstance(msg_key, bytes):
                msg_key = msg_key.decode()
            try:
                decoded = _json.loads(msg_key)
            except (ValueError, TypeError):
                decoded = msg_key
            if isinstance(decoded, dict):
                key = tuple(decoded.get(k) for k in self.primary_key)
            elif len(self.primary_key) == 1:
                key = (decoded,)
            else:
                raise ValueError(
                    "tombstone key must be a JSON object for a composite "
                    "primary key"
                )
            return [ParsedEvent(UPSERT, None, key=key)]
        obj = self.registry.decode_message(value)
        values = tuple(obj.get(name) for name in self.column_names)
        if self.primary_key:
            key = tuple(obj.get(k) for k in self.primary_key)
            return [ParsedEvent(UPSERT, values, key=key)]
        return [ParsedEvent(INSERT, values)]


class _AvroRegistryFormatter:
    """Row -> schema-registry framed Avro message (write side)."""

    def __init__(self, registry: SchemaRegistry, schema_id: int) -> None:
        self.registry = registry
        self.schema_id = schema_id

    def format(self, key, values, column_names, time, diff):
        obj = {name: v for name, v in zip(column_names, values)}
        obj["time"] = time
        obj["diff"] = diff
        return self.registry.encode_message(self.schema_id, obj)


def read(
    rdkafka_settings: dict | None = None,
    topic: str | list[str] | None = None,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "raw",  # noqa: A002
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    primary_key: Sequence[str] | None = None,
    transport: Any = None,
    schema_registry: SchemaRegistry | None = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read a topic. ``format``: 'raw'/'plaintext' (single ``data``
    column), 'json' (schema columns; with ``primary_key`` the stream is
    an upsert stream — later messages for a key replace earlier ones,
    reference SessionType::Upsert adaptors.rs:48), or 'avro'
    (schema-registry framed messages, needs ``schema_registry=``).
    ``mode='static'`` reads to the topic end offset and finishes."""
    if transport is None:
        transport = _default_transport(rdkafka_settings or {}, topic, mode)

    if format in ("raw", "plaintext"):
        schema = schema_mod.schema_from_types(
            data=bytes if format == "raw" else str
        )
        make_parser = lambda names: _KafkaRawParser(binary=format == "raw")  # noqa: E731
    elif format == "json":
        if schema is None:
            raise ValueError("format='json' needs schema=")
        pk = primary_key or schema.primary_key_columns() or None
        make_parser = lambda names: _KafkaJsonParser(names, pk)  # noqa: E731
    elif format == "avro":
        if schema is None:
            raise ValueError("format='avro' needs schema=")
        if schema_registry is None:
            raise ValueError("format='avro' needs schema_registry=")
        pk = primary_key or schema.primary_key_columns() or None
        make_parser = lambda names: _KafkaAvroParser(  # noqa: E731
            names, pk, schema_registry
        )
    else:
        raise ValueError(f"unknown kafka format {format!r}")

    return input_table(
        schema,
        lambda: MessageQueueReader(transport),
        make_parser,
        source_name=f"kafka:{topic}",
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def simple_read(
    server: str, topic: str, *, transport: Any = None, **kwargs: Any
) -> Table:
    """Reference simple_read (kafka/__init__.py:299): bare-bones raw read."""
    return read(
        {"bootstrap.servers": server}, topic, transport=transport, **kwargs
    )


def write(
    table: Table,
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    *,
    format: str = "json",  # noqa: A002
    key: str | None = None,
    transport: Any = None,
    schema_registry: SchemaRegistry | None = None,
    **kwargs: Any,
) -> None:
    """Produce one message per change. ``format='json'`` emits the row +
    time + diff as JSON; ``format='avro'`` registers the table schema
    under ``{topic}-value`` and emits schema-registry framed Avro."""
    if transport is None:
        transport = _default_transport(rdkafka_settings or {}, topic_name)
    if format == "json":
        formatter: Any = JsonLinesFormatter()
    elif format == "avro":
        if schema_registry is None:
            raise ValueError("format='avro' needs schema_registry=")
        avro_schema = _avro_schema_of(
            table.schema, (topic_name or "table") + "_value"
        )
        avro_schema["fields"] += [
            {"name": "time", "type": "long"},
            {"name": "diff", "type": "long"},
        ]
        schema_id = schema_registry.register(
            f"{topic_name or 'table'}-value", avro_schema
        )
        formatter = _AvroRegistryFormatter(schema_registry, schema_id)
    else:
        raise ValueError(f"unsupported kafka write format {format!r}")

    def make_writer(column_names):
        key_index = column_names.index(key) if key else None
        return MessageQueueWriter(
            transport, formatter, column_names, key_index=key_index
        )

    attach_writer(table, make_writer)


def read_from_upstash(
    endpoint: str,
    username: str,
    password: str,
    topic: str,
    *,
    consumer_group: str = "pathway-group",
    instance_name: str = "pathway-instance",
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "raw",  # noqa: A002
    autocommit_duration_ms: int | None = 1500,
    primary_key: Sequence[str] | None = None,
    request_fn: Callable[[str, dict], list] | None = None,
    **kwargs: Any,
) -> Table:
    """Consume a topic through the Upstash Kafka REST API (reference
    kafka/__init__.py read_from_upstash): repeated POSTs to
    ``{endpoint}/consume/{group}/{instance}/{topic}`` with basic auth;
    each response item is ``{"key","value","offset","partition",...}``.
    ``request_fn(url, headers) -> list`` is injectable for offline use."""
    from pathway_tpu.engine.storage import Message

    if request_fn is None:

        def request_fn(url: str, headers: dict) -> list:  # pragma: no cover
            import urllib.request

            req = urllib.request.Request(url, method="POST", headers=headers)
            with urllib.request.urlopen(req, timeout=60) as resp:
                return _json.loads(resp.read().decode())

    import base64

    auth = base64.b64encode(f"{username}:{password}".encode()).decode()
    url = (
        f"{endpoint.rstrip('/')}/consume/{consumer_group}/"
        f"{instance_name}/{topic}"
    )
    headers = {"Authorization": f"Basic {auth}"}

    # an injected request_fn may carry a ``finished`` callable to end the
    # stream (tests / bounded replays); the real REST consume never ends
    finished_fn = getattr(request_fn, "finished", None)

    class _UpstashTransport:
        def poll_messages(self) -> list:
            out = []
            for item in request_fn(url, headers):
                value = item.get("value")
                if isinstance(value, str):
                    value = value.encode()
                msg_key = item.get("key")
                if isinstance(msg_key, str):
                    msg_key = msg_key.encode()
                out.append(
                    Message(
                        value,
                        key=msg_key,
                        topic=item.get("topic", topic),
                        partition=item.get("partition", 0),
                        offset=item.get("offset", 0),
                    )
                )
            return out

        def finished(self) -> bool:
            return bool(finished_fn()) if finished_fn is not None else False

    return read(
        None,
        topic,
        schema=schema,
        format=format,
        autocommit_duration_ms=autocommit_duration_ms,
        primary_key=primary_key,
        transport=_UpstashTransport(),
        **kwargs,
    )
