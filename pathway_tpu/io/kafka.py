"""pw.io.kafka — Kafka-shaped message-queue connector
(reference: python/pathway/io/kafka/__init__.py; KafkaReader
src/connectors/data_storage.rs:673, KafkaWriter :1239).

No Kafka client library ships in this image, so the broker is reached
through an injectable **transport** (``MessageTransport``: poll_messages /
finished / produce). ``transport=None`` tries confluent-kafka and raises a
clear error when absent; tests and demos inject
:class:`pathway_tpu.engine.storage.InMemoryTransport`.
"""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.engine.connectors import (
    INSERT,
    UPSERT,
    JsonLinesFormatter,
    Parser,
    ParsedEvent,
)
from pathway_tpu.engine.storage import (
    InMemoryTransport,
    MessageQueueReader,
    MessageQueueWriter,
)
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, input_table

__all__ = ["read", "write", "simple_read", "InMemoryTransport"]


class _KafkaJsonParser(Parser):
    """value bytes -> JSON object -> schema columns; keyed by primary key
    columns when given (upsert session, like the reference's Kafka+json
    upsert path), else plain inserts."""

    def __init__(
        self, column_names: Sequence[str], primary_key: Sequence[str] | None
    ) -> None:
        super().__init__(column_names)
        self.primary_key = list(primary_key) if primary_key else None
        self.session_type = "upsert" if self.primary_key else "native"

    def parse(self, payload: Any) -> list[ParsedEvent]:
        import json

        from pathway_tpu.engine.value import Json

        msg_key, value = payload
        if value is None:
            # compacted-topic tombstone: with a primary key it deletes the
            # row whose key matches the message key (JSON-decoded when
            # possible, raw string otherwise)
            if not self.primary_key or msg_key is None:
                return []
            if isinstance(msg_key, bytes):
                msg_key = msg_key.decode("utf-8")
            try:
                decoded = json.loads(msg_key)
            except (ValueError, TypeError):
                decoded = msg_key
            if isinstance(decoded, dict):
                key = tuple(decoded.get(k) for k in self.primary_key)
            elif len(self.primary_key) == 1:
                key = (decoded,)
            else:
                raise ValueError(
                    "tombstone key must be a JSON object for a composite "
                    "primary key"
                )
            return [ParsedEvent(UPSERT, None, key=key)]
        if isinstance(value, bytes):
            value = value.decode("utf-8")
        obj = json.loads(value)
        values = tuple(
            Json(v) if isinstance(v, (dict, list)) else v
            for v in (obj.get(name) for name in self.column_names)
        )
        if self.primary_key:
            key = tuple(obj.get(k) for k in self.primary_key)
            return [ParsedEvent(UPSERT, values, key=key)]
        return [ParsedEvent(INSERT, values)]


class _KafkaRawParser(Parser):
    """value bytes -> single `data` column (format='raw'/'plaintext')."""

    def __init__(self, binary: bool) -> None:
        super().__init__(["data"])
        self.binary = binary

    def parse(self, payload: Any) -> list[ParsedEvent]:
        _key, value = payload
        if value is None:
            return []
        if self.binary and isinstance(value, str):
            value = value.encode("utf-8")
        if not self.binary and isinstance(value, bytes):
            value = value.decode("utf-8")
        return [ParsedEvent(INSERT, (value,))]


def _default_transport(rdkafka_settings: dict, topic: str, **kwargs: Any):
    try:
        import confluent_kafka  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "pw.io.kafka needs confluent-kafka (not installed here); pass "
            "transport=<MessageTransport> to read without it"
        ) from e
    raise NotImplementedError(
        "confluent-kafka transport wiring requires a live broker"
    )


def read(
    rdkafka_settings: dict | None = None,
    topic: str | list[str] | None = None,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "raw",  # noqa: A002
    autocommit_duration_ms: int | None = 1500,
    primary_key: Sequence[str] | None = None,
    transport: Any = None,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read a topic. ``format``: 'raw'/'plaintext' (single ``data``
    column), or 'json' (schema columns; with ``primary_key`` the stream is
    an upsert stream — later messages for a key replace earlier ones,
    reference SessionType::Upsert adaptors.rs:48)."""
    if transport is None:
        transport = _default_transport(rdkafka_settings or {}, topic)

    if format in ("raw", "plaintext"):
        schema = schema_mod.schema_from_types(
            data=bytes if format == "raw" else str
        )
        make_parser = lambda names: _KafkaRawParser(binary=format == "raw")  # noqa: E731
    elif format == "json":
        if schema is None:
            raise ValueError("format='json' needs schema=")
        pk = primary_key or schema.primary_key_columns() or None
        make_parser = lambda names: _KafkaJsonParser(names, pk)  # noqa: E731
    else:
        raise ValueError(f"unknown kafka format {format!r}")

    return input_table(
        schema,
        lambda: MessageQueueReader(transport),
        make_parser,
        source_name=f"kafka:{topic}",
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def simple_read(
    server: str, topic: str, *, transport: Any = None, **kwargs: Any
) -> Table:
    """Reference simple_read (kafka/__init__.py:299): bare-bones raw read."""
    return read(
        {"bootstrap.servers": server}, topic, transport=transport, **kwargs
    )


def write(
    table: Table,
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    *,
    format: str = "json",  # noqa: A002
    key: str | None = None,
    transport: Any = None,
    **kwargs: Any,
) -> None:
    """Produce one message per change (JSON row + time + diff)."""
    if transport is None:
        transport = _default_transport(rdkafka_settings or {}, topic_name)
    if format != "json":
        raise ValueError(f"unsupported kafka write format {format!r}")

    def make_writer(column_names):
        key_index = column_names.index(key) if key else None
        return MessageQueueWriter(
            transport, JsonLinesFormatter(), column_names, key_index=key_index
        )

    attach_writer(table, make_writer)
