"""pw.io.slack — send table updates as Slack messages
(reference: python/pathway/io/slack/__init__.py send_alerts)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.value import Json, Pointer
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer

_SLACK_URL = "https://slack.com/api/chat.postMessage"


class _SlackWriter:
    def __init__(
        self,
        channel: str,
        token: str,
        column_names,
        post_fn: Callable[[str, dict, dict], Any] | None,
    ) -> None:
        self.channel = channel
        self.token = token
        self.column_names = list(column_names)
        if post_fn is None:
            import requests

            post_fn = lambda url, headers, payload: requests.post(  # noqa: E731
                url, headers=headers, json=payload, timeout=30
            ).raise_for_status()
        self.post_fn = post_fn

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        if diff <= 0:
            return  # alerts are fire-once; retractions are not re-sent
        v = values[0]
        text = str(v.value if isinstance(v, Json) else v)
        self.post_fn(
            _SLACK_URL,
            {"Authorization": f"Bearer {self.token}"},
            {"channel": self.channel, "text": text},
        )

    def on_time_end(self, time: int) -> None:
        pass

    def on_end(self) -> None:
        pass


def send_alerts(
    alerts: Table,
    slack_channel_id: str,
    slack_token: str,
    *,
    post_fn: Callable[[str, dict, dict], Any] | None = None,
) -> None:
    """Post the first column of every inserted row as a message."""

    def make_writer(column_names):
        return _SlackWriter(slack_channel_id, slack_token, column_names, post_fn)

    attach_writer(alerts, make_writer)
