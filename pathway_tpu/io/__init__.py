"""pw.io — connectors (reference: python/pathway/io/, 27 modules).

Implemented natively: fs, csv, jsonlines, plaintext, python, null,
subscribe. Remote-service connectors (kafka, s3, deltalake, ...) are gated on
their client libraries being present.
"""

from pathway_tpu.io import csv, fs, http, jsonlines, null, plaintext, python
from pathway_tpu.io._subscribe import subscribe

__all__ = [
    "csv",
    "fs",
    "http",
    "jsonlines",
    "null",
    "plaintext",
    "python",
    "subscribe",
]
