"""pw.io — connectors (reference: python/pathway/io/, 27 modules).

Local-native: fs, csv, jsonlines, plaintext, python, null, http, sqlite,
deltalake, subscribe. Service connectors (kafka, redpanda, nats, debezium,
s3, minio, postgres, elasticsearch, mongodb, bigquery, pubsub, slack,
logstash, gdrive, pyfilesystem) reach their service through an injectable
transport/client seam — live deployments adapt the vendor SDK, tests run
against in-memory fakes; where no client can exist here the entry point is
gated with a clear error (iceberg, airbyte).
"""

from pathway_tpu.io import (
    airbyte,
    bigquery,
    csv,
    debezium,
    deltalake,
    elasticsearch,
    fs,
    gdrive,
    http,
    iceberg,
    jsonlines,
    kafka,
    logstash,
    minio,
    mongodb,
    nats,
    null,
    plaintext,
    postgres,
    pubsub,
    pyfilesystem,
    python,
    redpanda,
    s3,
    s3_csv,
    slack,
    sqlite,
)
from pathway_tpu.io._subscribe import subscribe
from pathway_tpu.io._synchronization import register_input_synchronization_group

__all__ = [
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "deltalake",
    "elasticsearch",
    "fs",
    "gdrive",
    "http",
    "iceberg",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "null",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "s3_csv",
    "slack",
    "sqlite",
    "subscribe",
    "register_input_synchronization_group",
]
