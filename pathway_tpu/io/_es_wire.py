"""Elasticsearch HTTP wire: a real ``_bulk`` client + in-process fake.

The reference ElasticSearchWriter (src/connectors/data_storage.rs:1317)
indexes one JSON document per change through the ES client library;
here the HTTP protocol itself is implemented: NDJSON action/source
pairs POSTed to ``/_bulk`` (the ES bulk API wire format), with
Basic / Bearer / ApiKey authorization headers, batched per engine
commit so a 1M-row commit is a handful of HTTP round trips rather than
a million.

The fake server speaks the same endpoints — POST ``/_bulk`` (parsing
the NDJSON frames, item-level results, ``errors`` flag), GET
``/{index}/_search`` and ``/{index}/_count`` for assertions — with
auth validation, so round-trip tests exercise genuine frames.
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import urlparse


class EsError(Exception):
    """Bulk item failure or HTTP-level error from the server."""


class EsBulkClient:
    """``index(index_name, document)`` + ``flush()``: documents buffer
    locally and travel as one ``/_bulk`` NDJSON request per flush (or
    when the buffer reaches ``max_batch``)."""

    def __init__(
        self,
        host: str,
        *,
        auth_header: str | None = None,
        max_batch: int = 2000,
        timeout: float = 30.0,
    ) -> None:
        parsed = urlparse(host if "://" in host else f"http://{host}")
        self._https = parsed.scheme == "https"
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or (443 if self._https else 9200)
        self._auth = auth_header
        self._timeout = timeout
        self.max_batch = max_batch
        self._buffer: list[tuple[str, dict]] = []

    def index(self, index_name: str, document: dict) -> None:
        self._buffer.append((index_name, document))
        if len(self._buffer) >= self.max_batch:
            self.flush()

    def flush(self) -> None:
        if not self._buffer:
            return
        lines = []
        for index_name, doc in self._buffer:
            lines.append(json.dumps({"index": {"_index": index_name}}))
            lines.append(json.dumps(doc))
        body = ("\n".join(lines) + "\n").encode("utf-8")
        resp = self._request("POST", "/_bulk", body)
        if resp.get("errors"):
            failed = [
                item["index"].get("error")
                for item in resp.get("items", ())
                if item.get("index", {}).get("error")
            ]
            # ES bulk is PER-ITEM: the good documents are already
            # indexed. Clear the buffer before raising so a retried
            # flush cannot re-post (duplicate) them; the failed items
            # surface through the error, not a resend loop.
            self._buffer = []
            raise EsError(f"bulk errors: {failed[:3]!r}")
        self._buffer = []

    def _request(self, method: str, path: str, body: bytes) -> dict:
        conn_cls = (
            http.client.HTTPSConnection
            if self._https
            else http.client.HTTPConnection
        )
        conn = conn_cls(self._host, self._port, timeout=self._timeout)
        try:
            headers = {"Content-Type": "application/x-ndjson"}
            if self._auth:
                headers["Authorization"] = self._auth
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status >= 400:
                raise EsError(
                    f"{resp.status}: {payload[:200].decode('utf-8', 'replace')}"
                )
            return json.loads(payload) if payload else {}
        finally:
            conn.close()

    def close(self) -> None:
        self.flush()


def auth_header_basic(username: str, password: str) -> str:
    cred = base64.b64encode(f"{username}:{password}".encode()).decode()
    return f"Basic {cred}"


def auth_header_bearer(token: str) -> str:
    return f"Bearer {token}"


def auth_header_apikey(apikey_id: str, apikey: str) -> str:
    cred = base64.b64encode(f"{apikey_id}:{apikey}".encode()).decode()
    return f"ApiKey {cred}"


class FakeElasticsearchServer:
    """In-process ES speaking the bulk/search endpoints over HTTP."""

    def __init__(self, *, auth_header: str | None = None) -> None:
        self.auth_header = auth_header
        #: index name -> list of stored documents, in arrival order
        self.indices: dict[str, list[dict]] = {}
        self.bulk_requests: list[int] = []  # docs per _bulk call
        self._lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:
                pass

            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authed(self) -> bool:
                if server.auth_header is None:
                    return True
                if self.headers.get("Authorization") == server.auth_header:
                    return True
                self._reply(
                    401,
                    {"error": {"type": "security_exception"}},
                )
                return False

            def do_POST(self) -> None:  # noqa: N802 — http.server API
                if not self._authed():
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length).decode("utf-8")
                if self.path.rstrip("/") != "/_bulk":
                    self._reply(404, {"error": "no route"})
                    return
                lines = [ln for ln in body.split("\n") if ln.strip()]
                items = []
                count = 0
                i = 0
                while i < len(lines):
                    action = json.loads(lines[i])
                    if "index" not in action:
                        items.append(
                            {
                                "index": {
                                    "status": 400,
                                    "error": {
                                        "type": "illegal_argument",
                                        "reason": f"unsupported action "
                                        f"{list(action)[:1]}",
                                    },
                                }
                            }
                        )
                        i += 1
                        continue
                    doc = json.loads(lines[i + 1])
                    idx = action["index"]["_index"]
                    with server._lock:
                        server.indices.setdefault(idx, []).append(doc)
                    items.append(
                        {"index": {"_index": idx, "status": 201}}
                    )
                    count += 1
                    i += 2
                with server._lock:
                    server.bulk_requests.append(count)
                self._reply(
                    200,
                    {
                        "took": 1,
                        "errors": any(
                            it["index"].get("error") for it in items
                        ),
                        "items": items,
                    },
                )

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if not self._authed():
                    return
                parts = self.path.strip("/").split("/")
                if len(parts) == 2 and parts[1] == "_search":
                    with server._lock:
                        docs = list(server.indices.get(parts[0], ()))
                    self._reply(
                        200,
                        {
                            "hits": {
                                "total": {"value": len(docs)},
                                "hits": [
                                    {"_source": d} for d in docs
                                ],
                            }
                        },
                    )
                    return
                if len(parts) == 2 and parts[1] == "_count":
                    with server._lock:
                        n = len(server.indices.get(parts[0], ()))
                    self._reply(200, {"count": n})
                    return
                self._reply(404, {"error": "no route"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        ).start()

    def host(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
