"""pw.io.pubsub — publish update streams to Google Pub/Sub (reference:
python/pathway/io/pubsub/__init__.py).

The REST protocol is implemented here (:class:`RestPublisher`:
``POST {base}/v1/projects/{p}/topics/{t}:publish`` with base64 message
data), reachable through ``project_id=`` + ``access_token=`` or a custom
``http_fn``; tests round-trip against an in-process HTTP fake. The
``publish(topic, data, **attrs)`` publisher seam remains for
google-cloud-pubsub."""

from __future__ import annotations

import base64 as _base64
import json
from typing import Any, Callable

from pathway_tpu.engine.connectors import JsonLinesFormatter
from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require

PUBSUB_API = "https://pubsub.googleapis.com"


class RestPublisher:
    """Speaks the Pub/Sub ``topics.publish`` REST endpoint."""

    def __init__(
        self,
        project_id: str,
        api_base: str = PUBSUB_API,
        access_token: str | None = None,
        http_fn: Callable[[str, dict], dict] | None = None,
    ) -> None:
        self.project_id = project_id
        self.api_base = api_base.rstrip("/")
        if http_fn is None:
            from pathway_tpu.io._utils import post_json

            def http_fn(url: str, payload: dict) -> dict:
                return post_json(url, payload, token=access_token)

        self.http_fn = http_fn

    def publish(self, topic: str, data: bytes, **attrs: Any) -> str:
        url = (
            f"{self.api_base}/v1/projects/{self.project_id}/topics/"
            f"{topic}:publish"
        )
        message: dict[str, Any] = {
            "data": _base64.b64encode(data).decode()
        }
        if attrs:
            message["attributes"] = {k: str(v) for k, v in attrs.items()}
        body = self.http_fn(url, {"messages": [message]})
        ids = body.get("messageIds") or [""]
        return ids[0]


class _PubSubWriter:
    def __init__(self, publisher: Any, topic: str, column_names):
        self.publisher = publisher
        self.topic = topic
        self.formatter = JsonLinesFormatter()
        self.column_names = list(column_names)

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        payload = self.formatter.format(
            key, values, self.column_names, time, diff
        )
        self.publisher.publish(self.topic, payload.encode("utf-8"))

    def on_time_end(self, time: int) -> None:
        pass

    def on_end(self) -> None:
        pass


def write(
    table: Table,
    publisher: Any = None,
    project_id: str | None = None,
    topic_id: str | None = None,
    *,
    access_token: str | None = None,
    api_base: str | None = None,
    **kwargs: Any,
) -> None:
    """Publish the update log. Publisher resolution: explicit
    ``publisher=`` seam; else the built-in REST publisher when
    ``api_base=`` or ``access_token=`` is given; else
    google-cloud-pubsub."""
    if publisher is None and project_id is not None and (
        api_base is not None or access_token is not None
    ):
        publisher = RestPublisher(
            project_id,
            api_base=api_base or PUBSUB_API,
            access_token=access_token,
        )
    if publisher is None:
        pubsub = require("google.cloud.pubsub_v1", "pw.io.pubsub")
        client = pubsub.PublisherClient()
        topic = client.topic_path(project_id, topic_id)

        class _Adapter:
            def publish(self, _topic, data: bytes, **attrs):
                client.publish(topic, data, **attrs).result()

        publisher = _Adapter()
    topic = topic_id or ""

    def make_writer(column_names):
        return _PubSubWriter(publisher, topic, column_names)

    attach_writer(table, make_writer)
