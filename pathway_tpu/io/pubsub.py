"""pw.io.pubsub — publish update streams to Google Pub/Sub (reference:
python/pathway/io/pubsub/__init__.py). Publisher seam:
``publish(topic, data: bytes, **attrs)``."""

from __future__ import annotations

import json
from typing import Any

from pathway_tpu.engine.connectors import JsonLinesFormatter
from pathway_tpu.engine.value import Pointer
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require


class _PubSubWriter:
    def __init__(self, publisher: Any, topic: str, column_names):
        self.publisher = publisher
        self.topic = topic
        self.formatter = JsonLinesFormatter()
        self.column_names = list(column_names)

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        payload = self.formatter.format(
            key, values, self.column_names, time, diff
        )
        self.publisher.publish(self.topic, payload.encode("utf-8"))

    def on_time_end(self, time: int) -> None:
        pass

    def on_end(self) -> None:
        pass


def write(
    table: Table,
    publisher: Any = None,
    project_id: str | None = None,
    topic_id: str | None = None,
    **kwargs: Any,
) -> None:
    if publisher is None:
        pubsub = require("google.cloud.pubsub_v1", "pw.io.pubsub")
        client = pubsub.PublisherClient()
        topic = client.topic_path(project_id, topic_id)

        class _Adapter:
            def publish(self, _topic, data: bytes, **attrs):
                client.publish(topic, data, **attrs).result()

        publisher = _Adapter()
    topic = topic_id or ""

    def make_writer(column_names):
        return _PubSubWriter(publisher, topic, column_names)

    attach_writer(table, make_writer)
