"""pw.io.gdrive — poll a Google Drive folder (reference:
python/pathway/io/gdrive/__init__.py, 405 LoC: service-account polling +
file diffing). Drive is reached through an injected ``service`` with
``list_files(folder_id) -> [(file_id, version)]`` and
``download(file_id) -> bytes``; the ObjectStore reader provides the
new/changed/deleted diffing."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.storage import ObjectStoreReader
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table, require


class _DriveStore:
    def __init__(self, service: Any, object_id: str) -> None:
        self.service = service
        self.object_id = object_id

    def list_objects(self, prefix: str):
        return list(self.service.list_files(self.object_id))

    def get_object(self, key: str) -> bytes:
        return self.service.download(key)


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    service_user_credentials_file: str | None = None,
    service: Any = None,
    with_metadata: bool = False,
    **kwargs: Any,
) -> Table:
    """Each Drive file becomes one binary `data` row; edits replace the
    previous row, deletions retract it."""
    if service is None:
        require("googleapiclient", "pw.io.gdrive")
        raise NotImplementedError(
            "gdrive service wiring requires credentials; pass service="
        )
    schema = schema_mod.schema_from_types(data=bytes)
    store = _DriveStore(service, object_id)

    from pathway_tpu.engine.connectors import IdentityParser

    return input_table(
        schema,
        lambda: ObjectStoreReader(store, "", mode=mode, binary=True),
        lambda names: IdentityParser(binary=True),
        source_name=f"gdrive:{object_id}",
        with_metadata=with_metadata,
    )
