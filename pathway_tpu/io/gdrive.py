"""pw.io.gdrive — poll a Google Drive folder (reference:
python/pathway/io/gdrive/__init__.py: service-account polling + file
diffing, ~405 LoC).

This is a real Drive REST v3 poller, not a seam: it speaks the
``files.list`` / ``files.get?alt=media`` / ``files.export`` endpoints
(recursive folder traversal, ``modifiedTime``-based change diffing,
deletion/trash retraction, Google-Docs export to plain formats) over an
injectable ``http_fn(url, params, headers) -> bytes``. The default
``http_fn`` uses urllib with a bearer token from either
``access_token=`` or a service-account credentials file (JWT grant,
RS256-signed via the ``cryptography`` package; absent that, pass
``access_token=`` or ``http_fn=``). Tests run against an in-process
fake Drive HTTP server, exercising the actual REST protocol.
"""

from __future__ import annotations

import json
import time as _time
import urllib.parse
import urllib.request
from typing import Any, Callable

from pathway_tpu.engine.connectors import UPSERT, ParsedEvent, Parser, Reader
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import input_table

DRIVE_API = "https://www.googleapis.com/drive/v3"

FOLDER_MIME = "application/vnd.google-apps.folder"

#: Google-native types have no binary content; they are exported
#: (reference gdrive connector's export behavior)
EXPORT_MIMES = {
    "application/vnd.google-apps.document": "text/plain",
    "application/vnd.google-apps.spreadsheet": "text/csv",
    "application/vnd.google-apps.presentation": "application/pdf",
}

_LIST_FIELDS = (
    "nextPageToken,files(id,name,mimeType,modifiedTime,size,trashed,parents)"
)


def _default_http_fn(token: str) -> Callable[[str, dict, dict], bytes]:
    def http_fn(url: str, params: dict, headers: dict) -> bytes:
        if params:
            url = url + "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(
            url, headers={"Authorization": f"Bearer {token}", **headers}
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.read()

    return http_fn


def _service_account_token(credentials_file: str) -> str:
    """OAuth2 JWT-bearer grant for a service account (drive.readonly)."""
    with open(credentials_file) as f:
        creds = json.load(f)
    try:
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise ImportError(
            "service-account auth needs the 'cryptography' package for "
            "RS256 signing; pass access_token= or http_fn= instead"
        ) from e
    import base64

    def b64(data: bytes) -> bytes:
        return base64.urlsafe_b64encode(data).rstrip(b"=")

    now = int(_time.time())
    header = b64(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    claims = b64(
        json.dumps(
            {
                "iss": creds["client_email"],
                "scope": "https://www.googleapis.com/auth/drive.readonly",
                "aud": "https://oauth2.googleapis.com/token",
                "iat": now,
                "exp": now + 3600,
            }
        ).encode()
    )
    signing_input = header + b"." + claims
    key = serialization.load_pem_private_key(
        creds["private_key"].encode(), password=None
    )
    signature = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    assertion = (signing_input + b"." + b64(signature)).decode()
    body = urllib.parse.urlencode(
        {
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": assertion,
        }
    ).encode()
    req = urllib.request.Request(
        "https://oauth2.googleapis.com/token", data=body, method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as resp:  # pragma: no cover
        return json.loads(resp.read().decode())["access_token"]


class GDriveClient:
    """Drive REST v3 subset: recursive listing + content download."""

    def __init__(
        self,
        http_fn: Callable[[str, dict, dict], bytes],
        api_base: str = DRIVE_API,
    ) -> None:
        self.http_fn = http_fn
        self.api_base = api_base.rstrip("/")

    def _get_json(self, path: str, params: dict) -> dict:
        return json.loads(
            self.http_fn(f"{self.api_base}{path}", params, {}).decode()
        )

    def list_folder(self, folder_id: str) -> list[dict]:
        """All non-trashed, non-folder files under ``folder_id``,
        recursively (folders are traversed, files collected)."""
        out: list[dict] = []
        pending = [folder_id]
        seen_folders = set()
        while pending:
            fid = pending.pop()
            if fid in seen_folders:
                continue  # cycles via multi-parent links
            seen_folders.add(fid)
            page_token: str | None = None
            while True:
                params: dict[str, Any] = {
                    "q": f"'{fid}' in parents and trashed = false",
                    "fields": _LIST_FIELDS,
                    "pageSize": 1000,
                }
                if page_token:
                    params["pageToken"] = page_token
                body = self._get_json("/files", params)
                for f in body.get("files", []):
                    if f.get("mimeType") == FOLDER_MIME:
                        pending.append(f["id"])
                    else:
                        out.append(f)
                page_token = body.get("nextPageToken")
                if not page_token:
                    break
        return out

    def download(self, file: dict) -> bytes:
        mime = file.get("mimeType", "")
        if mime in EXPORT_MIMES:
            return self.http_fn(
                f"{self.api_base}/files/{file['id']}/export",
                {"mimeType": EXPORT_MIMES[mime]},
                {},
            )
        return self.http_fn(
            f"{self.api_base}/files/{file['id']}", {"alt": "media"}, {}
        )


class _GDrivePollReader(Reader):
    """Poll a folder; upsert new/modified files (keyed by file id),
    retract vanished/trashed ones — the reference connector's diffing."""

    def __init__(
        self,
        client: Any,
        folder_id: str,
        mode: str,
        refresh_interval_s: float,
    ) -> None:
        self.client = client
        self.folder_id = folder_id
        self.mode = mode
        self.refresh_interval_s = refresh_interval_s
        #: file id -> modifiedTime version last ingested
        self._known: dict[str, str] = {}
        self._last_poll = 0.0
        self._first = True

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        now = _time.monotonic()
        if not self._first and now - self._last_poll < self.refresh_interval_s:
            return [], False
        self._last_poll = now
        self._first = False
        files = {f["id"]: f for f in self.client.list_folder(self.folder_id)}
        events: list[tuple[Any, str, dict]] = []
        for fid, meta in files.items():
            version = meta.get("modifiedTime", "")
            if self._known.get(fid) == version:
                continue
            data = self.client.download(meta)
            self._known[fid] = version
            events.append((("upsert", fid, data), fid, dict(meta)))
        for fid in list(self._known):
            if fid not in files:
                del self._known[fid]
                events.append((("delete", fid, None), fid, {"id": fid}))
        return events, self.mode == "static"

    def state(self) -> dict:
        return {"known": dict(self._known)}

    def restore_state(self, state: dict) -> None:
        # versions suffice: content re-downloads only for changed files
        self._known = dict(state.get("known", {}))


class _GDriveParser(Parser):
    session_type = "upsert"

    def __init__(self) -> None:
        super().__init__(["data"])

    def parse(self, payload: Any) -> list[ParsedEvent]:
        kind, fid, data = payload
        if kind == "delete":
            return [ParsedEvent(UPSERT, None, key=(fid,))]
        return [ParsedEvent(UPSERT, (data,), key=(fid,))]


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    service_user_credentials_file: str | None = None,
    access_token: str | None = None,
    http_fn: Callable[[str, dict, dict], bytes] | None = None,
    api_base: str = DRIVE_API,
    refresh_interval: float = 30.0,
    with_metadata: bool = False,
    service: Any = None,
    **kwargs: Any,
) -> Table:
    """Each Drive file becomes one binary ``data`` row keyed by file id;
    edits replace the previous row, deletions/trash retract it. Google
    Docs/Sheets/Slides are exported (text/csv/pdf).

    Auth, in priority order: ``http_fn=`` (full transport override),
    ``access_token=``, or ``service_user_credentials_file=``
    (service-account JWT grant). The legacy ``service=`` seam
    (``list_files``/``download``) keeps working."""
    if service is not None:
        # legacy injectable seam, kept for compatibility
        class _SeamClient:
            def list_folder(self, folder_id: str) -> list[dict]:
                return [
                    {"id": fid, "modifiedTime": str(ver), "name": fid}
                    for fid, ver in service.list_files(folder_id)
                ]

            def download(self, file: dict) -> bytes:
                return service.download(file["id"])

        client: Any = _SeamClient()
    else:
        if http_fn is None:
            if access_token is not None:
                http_fn = _default_http_fn(access_token)
            elif service_user_credentials_file is None:
                raise ValueError(
                    "pw.io.gdrive.read needs one of http_fn=, "
                    "access_token= or service_user_credentials_file="
                )
            else:
                # service-account tokens expire after ~1h: re-mint with
                # headroom so a long streaming read never 401s mid-poll
                creds_file = service_user_credentials_file
                token_state = {"token": None, "exp": 0.0}

                def http_fn(url: str, params: dict, headers: dict) -> bytes:
                    now = _time.time()
                    if token_state["token"] is None or now > token_state["exp"]:
                        token_state["token"] = _service_account_token(
                            creds_file
                        )
                        token_state["exp"] = now + 3600 - 300
                    return _default_http_fn(token_state["token"])(
                        url, params, headers
                    )

        client = GDriveClient(http_fn, api_base=api_base)

    schema = schema_mod.schema_from_types(data=bytes)
    return input_table(
        schema,
        lambda: _GDrivePollReader(client, object_id, mode, refresh_interval),
        lambda names: _GDriveParser(),
        source_name=f"gdrive:{object_id}",
        with_metadata=with_metadata,
    )
