"""pw.io.deltalake — Delta Lake table connector
(reference: python/pathway/io/deltalake/__init__.py, 293 LoC;
src/connectors/data_lake/delta.rs).

The reference links the delta-rs crate. That library isn't in this image,
so this is a native implementation of the open Delta protocol subset the
connector needs: parquet data files (pyarrow) plus the ``_delta_log/``
JSON commit log — version files ``{v:020d}.json`` holding ``protocol`` /
``metaData`` / ``add`` actions. Tables written here open in any Delta
reader, and appends from other writers are picked up by the streaming
reader polling the log.
"""

from __future__ import annotations

import json
import os
import time as _time
import uuid
from typing import Any, Sequence

from pathway_tpu.engine.connectors import Reader
from pathway_tpu.engine.value import Json, Pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, input_table

_LOG_DIR = "_delta_log"


def _spark_type(dtype: dt.DType) -> str:
    base = dtype.strip_optional()
    if base == dt.INT:
        return "long"
    if base == dt.FLOAT:
        return "double"
    if base == dt.BOOL:
        return "boolean"
    if base == dt.BYTES:
        return "binary"
    return "string"


def _schema_string(column_names: Sequence[str], dtypes: dict) -> str:
    fields = [
        {
            "name": name,
            "type": _spark_type(dtypes.get(name, dt.STR)),
            "nullable": True,
            "metadata": {},
        }
        for name in column_names
    ]
    return json.dumps({"type": "struct", "fields": fields})


def _log_path(table_path: str, version: int) -> str:
    return os.path.join(table_path, _LOG_DIR, f"{version:020d}.json")


def _list_versions(table_path: str) -> list[int]:
    log_dir = os.path.join(table_path, _LOG_DIR)
    if not os.path.isdir(log_dir):
        return []
    out = []
    for name in os.listdir(log_dir):
        if name.endswith(".json"):
            try:
                out.append(int(name[:-5]))
            except ValueError:
                continue
    return sorted(out)


class DeltaWriter:
    """Append-only Delta writer: one parquet file + one log commit per
    engine commit (reference data_lake/writer.rs batching)."""

    def __init__(self, table_path: str, column_names: Sequence[str], dtypes: dict):
        self.table_path = os.fspath(table_path)
        self.column_names = list(column_names)
        self.dtypes = dtypes
        self._rows: list[tuple] = []
        os.makedirs(os.path.join(self.table_path, _LOG_DIR), exist_ok=True)
        if not _list_versions(self.table_path):
            self._commit(
                [
                    {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
                    {
                        "metaData": {
                            "id": str(uuid.uuid4()),
                            "format": {"provider": "parquet", "options": {}},
                            "schemaString": _schema_string(
                                self.column_names + ["time", "diff"],
                                {**dtypes, "time": dt.INT, "diff": dt.INT},
                            ),
                            "partitionColumns": [],
                            "configuration": {},
                            "createdTime": int(_time.time() * 1000),
                        }
                    },
                ]
            )

    def _commit(self, actions: list[dict]) -> None:
        version = (_list_versions(self.table_path) or [-1])[-1] + 1
        path = _log_path(self.table_path, version)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for action in actions:
                f.write(json.dumps(action) + "\n")
        os.replace(tmp, path)  # atomic publish, like delta's rename commit

    def on_change(self, key: Pointer, values: tuple, time: int, diff: int) -> None:
        row = tuple(
            json.dumps(v.value) if isinstance(v, Json) else v for v in values
        )
        self._rows.append(row + (time, diff))

    def on_time_end(self, time: int) -> None:
        if not self._rows:
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        names = self.column_names + ["time", "diff"]
        columns = list(zip(*self._rows))
        table = pa.table(
            {name: list(col) for name, col in zip(names, columns)}
        )
        fname = f"part-00000-{uuid.uuid4()}.parquet"
        fpath = os.path.join(self.table_path, fname)
        pq.write_table(table, fpath)
        self._rows = []
        self._commit(
            [
                {
                    "add": {
                        "path": fname,
                        "partitionValues": {},
                        "size": os.path.getsize(fpath),
                        "modificationTime": int(_time.time() * 1000),
                        "dataChange": True,
                    }
                }
            ]
        )

    def on_end(self) -> None:
        self.on_time_end(-1)


class DeltaReader(Reader):
    """Poll the Delta log; emit rows of newly-added parquet files. Rows
    written by a pathway writer carry time/diff columns — diff=-1 rows
    become retractions (the update-log round-trips)."""

    def __init__(
        self,
        table_path: str,
        column_names: Sequence[str],
        mode: str,
        key_indices: Sequence[int] | None = None,
    ):
        self.table_path = os.fspath(table_path)
        self.column_names = list(column_names)
        self.mode = mode
        self.key_indices = list(key_indices) if key_indices else None
        self._next_version = 0
        self._done_static = False

    def _events_of_file(self, fname: str):
        from pathway_tpu.io._utils import lake_parquet_events

        return lake_parquet_events(
            os.path.join(self.table_path, fname),
            self.column_names,
            self.key_indices,
            "delta",
        )

    def poll(self) -> tuple[list[tuple[Any, str, dict]], bool]:
        if self._done_static:
            return [], True
        entries = []
        for version in _list_versions(self.table_path):
            if version < self._next_version:
                continue
            with open(_log_path(self.table_path, version), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    action = json.loads(line)
                    if "add" in action:
                        fname = action["add"]["path"]
                        entries.append(
                            (
                                self._events_of_file(fname),
                                f"delta:{fname}",
                                {"path": fname},
                            )
                        )
            self._next_version = version + 1
        if self.mode == "static":
            self._done_static = True
        return entries, self.mode == "static"

    def state(self) -> dict:
        return {"next_version": self._next_version}

    def restore_state(self, state: dict) -> None:
        self._next_version = int(state.get("next_version", 0))
        self._done_static = False


def read(
    uri: str | os.PathLike,
    *,
    schema: schema_mod.SchemaMetaclass,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs: Any,
) -> Table:
    from pathway_tpu.engine.storage import TransparentParser

    column_names = schema.column_names()
    pk = schema.primary_key_columns()
    key_indices = [column_names.index(p) for p in pk] if pk else None
    return input_table(
        schema,
        lambda: DeltaReader(os.fspath(uri), column_names, mode, key_indices),
        lambda names: TransparentParser(names),
        source_name=f"deltalake:{uri}",
        persistent_id=persistent_id,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def write(
    table: Table,
    uri: str | os.PathLike,
    *,
    min_commit_frequency: int | None = None,
    **kwargs: Any,
) -> None:
    dtypes = dict(table._dtypes)

    def make_writer(column_names):
        return DeltaWriter(os.fspath(uri), column_names, dtypes)

    attach_writer(table, make_writer)
