"""PostgreSQL wire protocol (frontend/backend v3): a real client and an
in-process fake server speaking actual frames.

The client performs the startup handshake (StartupMessage ->
Authentication Ok/CleartextPassword -> ParameterStatus/BackendKeyData ->
ReadyForQuery) and runs every statement through the EXTENDED query
protocol — Parse('P') / Bind('B') / Execute('E') / Sync('S') — with the
``$N`` placeholders the Psql formatters already emit and text-format
parameters; BEGIN/COMMIT ride the simple-query path ('Q'), giving the
per-commit transactional batches of the reference PsqlWriter
(src/connectors/data_storage.rs:1061; message formats per the protocol
spec, postgresql.org/docs/current/protocol-message-formats.html).

The fake server accepts the same frames (including the SSLRequest
refusal and optional cleartext-password auth), interprets the three
statement shapes the formatters produce (update-log INSERT, snapshot
upsert INSERT..ON CONFLICT DO UPDATE, DELETE-by-key), and applies them
to in-memory tables with transaction staging — changes become visible
only at COMMIT, so tests can assert transactionality over real frames
(reference formatters: src/connectors/data_format.rs:1625,1684).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
import socket
import struct
import threading
from typing import Any

_PROTOCOL_V3 = 196608
_SSL_REQUEST = 80877103


def _scram_salted_password(password: str, salt: bytes, iters: int) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iters)


def _hmac256(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def _md5_password(user: str, password: str, salt: bytes) -> str:
    inner = hashlib.md5((password + user).encode()).hexdigest()
    return "md5" + hashlib.md5(inner.encode() + salt).hexdigest()


class PgError(Exception):
    """Server-reported error (ErrorResponse frame) or protocol failure."""


def _frame(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(b: str) -> bytes:
    return b.encode("utf-8") + b"\0"


def _error_fields(body: bytes) -> str:
    parts = []
    for chunk in body.split(b"\0"):
        if len(chunk) >= 2 and chunk[:1] in (b"S", b"C", b"M"):
            parts.append(chunk[1:].decode("utf-8", "replace"))
    return ": ".join(parts) if parts else body.decode("utf-8", "replace")


def encode_text_param(v: Any) -> bytes | None:
    """Python value -> postgres text-format parameter (None = SQL NULL).
    bytes use the bytea hex form; lists/tuples the array literal form."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, (bytes, bytearray)):
        return b"\\x" + bytes(v).hex().encode()
    if isinstance(v, (list, tuple)):
        items = []
        for item in v:
            if item is None:
                items.append("NULL")
            else:
                s = str(item).replace("\\", "\\\\").replace('"', '\\"')
                items.append(f'"{s}"')
        return ("{" + ",".join(items) + "}").encode("utf-8")
    return str(v).encode("utf-8")


def decode_text_param(b: bytes | None) -> Any:
    """Postgres text-format parameter -> Python value (used by the fake
    server so snapshot keys compare the way a typed database would)."""
    if b is None:
        return None
    s = b.decode("utf-8")
    if s == "t":
        return True
    if s == "f":
        return False
    if s.startswith("\\x"):
        try:
            return bytes.fromhex(s[2:])
        except ValueError:
            pass
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


class _FrameReader:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self._buf = b""

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise PgError("connection closed by peer")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def read_message(self) -> tuple[bytes, bytes]:
        head = self._read_exact(5)
        tag = head[:1]
        (length,) = struct.unpack(">I", head[1:5])
        return tag, self._read_exact(length - 4)

    def read_startup(self) -> tuple[int, dict[str, str]]:
        (length,) = struct.unpack(">I", self._read_exact(4))
        body = self._read_exact(length - 4)
        (code,) = struct.unpack(">I", body[:4])
        params: dict[str, str] = {}
        items = body[4:].split(b"\0")
        for k, v in zip(items[::2], items[1::2]):
            if k:
                params[k.decode()] = v.decode()
        return code, params


class PgWireConnection:
    """Wire-level connection with the executor contract PsqlWriter
    expects: ``execute(statement, params)`` + ``commit()`` (+ close)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 5432,
        user: str = "pathway",
        password: str | None = None,
        dbname: str = "pathway",
        connect_timeout: float = 10.0,
        sslmode: str = "prefer",
    ) -> None:
        if sslmode not in ("disable", "prefer", "require", "verify-full"):
            raise PgError(f"unsupported sslmode {sslmode!r}")
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        if sslmode != "disable":
            # SSLRequest: 'S' -> wrap in TLS, 'N' -> plaintext (libpq
            # 'require' errors on refusal, 'prefer' falls back)
            self.sock.sendall(struct.pack(">II", 8, _SSL_REQUEST))
            answer = self.sock.recv(1)
            if answer == b"S":
                import ssl

                ctx = ssl.create_default_context()
                if sslmode != "verify-full":
                    # libpq: only verify-full checks the chain AND the
                    # hostname; require accepts any certificate
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl.CERT_NONE
                self.sock = ctx.wrap_socket(self.sock, server_hostname=host)
            elif answer != b"N":
                raise PgError(f"unexpected SSLRequest answer {answer!r}")
            elif sslmode in ("require", "verify-full"):
                raise PgError(f"server refused SSL but sslmode={sslmode}")
        self._reader = _FrameReader(self.sock)
        self._in_txn = False
        params = (
            _cstr("user")
            + _cstr(user)
            + _cstr("database")
            + _cstr(dbname)
            + _cstr("client_encoding")
            + _cstr("UTF8")
            + b"\0"
        )
        payload = struct.pack(">I", _PROTOCOL_V3) + params
        self.sock.sendall(struct.pack(">I", len(payload) + 4) + payload)
        scram: dict[str, Any] = {}
        while True:
            tag, body = self._reader.read_message()
            if tag == b"R":
                (code,) = struct.unpack(">I", body[:4])
                if code == 0:
                    continue  # AuthenticationOk: wait for ReadyForQuery
                if code in (3, 5, 10, 11, 12) and password is None:
                    raise PgError("server requires a password")
                if code == 3:  # CleartextPassword
                    self.sock.sendall(_frame(b"p", _cstr(password)))
                elif code == 5:  # MD5Password
                    salt = body[4:8]
                    self.sock.sendall(
                        _frame(b"p", _cstr(_md5_password(user, password, salt)))
                    )
                elif code == 10:  # AuthenticationSASL
                    mechanisms = body[4:].split(b"\0")
                    if b"SCRAM-SHA-256" not in mechanisms:
                        raise PgError(
                            f"no supported SASL mechanism in {mechanisms!r}"
                        )
                    nonce = base64.b64encode(os.urandom(18)).decode()
                    first_bare = f"n=,r={nonce}"
                    scram = {"nonce": nonce, "first_bare": first_bare}
                    initial = ("n,," + first_bare).encode()
                    self.sock.sendall(
                        _frame(
                            b"p",
                            _cstr("SCRAM-SHA-256")
                            + struct.pack(">i", len(initial))
                            + initial,
                        )
                    )
                elif code == 11:  # SASLContinue: server-first-message
                    server_first = body[4:].decode()
                    fields = dict(
                        item.split("=", 1)
                        for item in server_first.split(",")
                    )
                    full_nonce = fields["r"]
                    if not full_nonce.startswith(scram["nonce"]):
                        raise PgError("SCRAM nonce mismatch")
                    salt = base64.b64decode(fields["s"])
                    iters = int(fields["i"])
                    salted = _scram_salted_password(password, salt, iters)
                    client_key = _hmac256(salted, b"Client Key")
                    stored_key = hashlib.sha256(client_key).digest()
                    final_bare = f"c=biws,r={full_nonce}"
                    auth_message = ",".join(
                        (scram["first_bare"], server_first, final_bare)
                    ).encode()
                    signature = _hmac256(stored_key, auth_message)
                    proof = bytes(
                        a ^ b for a, b in zip(client_key, signature)
                    )
                    scram["server_sig"] = _hmac256(
                        _hmac256(salted, b"Server Key"), auth_message
                    )
                    final = (
                        final_bare
                        + ",p="
                        + base64.b64encode(proof).decode()
                    ).encode()
                    self.sock.sendall(_frame(b"p", final))
                elif code == 12:  # SASLFinal: verify server signature
                    fields = dict(
                        item.split("=", 1)
                        for item in body[4:].decode().split(",")
                    )
                    expected = base64.b64encode(
                        scram["server_sig"]
                    ).decode()
                    if fields.get("v") != expected:
                        raise PgError("SCRAM server signature mismatch")
                else:
                    raise PgError(f"unsupported auth method {code}")
                continue
            if tag in (b"S", b"K", b"N"):
                continue  # ParameterStatus / BackendKeyData / Notice
            if tag == b"Z":
                break  # ReadyForQuery
            if tag == b"E":
                raise PgError(_error_fields(body))
            raise PgError(f"unexpected startup frame {tag!r}")
        # connect_timeout bounds ONLY establishment + handshake; a slow
        # statement on a loaded server must not desync the stream
        self.sock.settimeout(None)

    # -- query paths --------------------------------------------------------

    def _drain_to_ready(self) -> None:
        error: str | None = None
        while True:
            tag, body = self._reader.read_message()
            if tag == b"Z":
                if error is not None:
                    raise PgError(error)
                return
            if tag == b"E":
                error = _error_fields(body)

    def _simple(self, query: str) -> None:
        self.sock.sendall(_frame(b"Q", _cstr(query)))
        self._drain_to_ready()

    def execute(self, statement: str, params: list) -> None:
        """Extended-protocol round trip: Parse/Bind/Execute/Sync. The
        first statement after a commit opens a transaction, matching the
        reference's per-time batches."""
        if not self._in_txn:
            self._simple("BEGIN")
            self._in_txn = True
        parse = _cstr("") + _cstr(statement) + struct.pack(">H", 0)
        bind = _cstr("") + _cstr("") + struct.pack(">H", 0)
        bind += struct.pack(">H", len(params))
        for p in params:
            enc = encode_text_param(p)
            if enc is None:
                bind += struct.pack(">i", -1)
            else:
                bind += struct.pack(">i", len(enc)) + enc
        bind += struct.pack(">H", 0)  # result formats: all text
        execute = _cstr("") + struct.pack(">i", 0)
        self.sock.sendall(
            _frame(b"P", parse)
            + _frame(b"B", bind)
            + _frame(b"E", execute)
            + _frame(b"S", b"")
        )
        try:
            self._drain_to_ready()
        except PgError:
            # postgres aborts the whole transaction on a statement error:
            # roll it back explicitly so (a) a real server does not treat
            # the eventual COMMIT as a silent ROLLBACK and (b) the next
            # execute() opens a fresh batch
            self._in_txn = False
            try:
                self._simple("ROLLBACK")
            except (PgError, OSError):
                pass
            raise

    def commit(self) -> None:
        if self._in_txn:
            self._simple("COMMIT")
            self._in_txn = False

    def close(self) -> None:
        try:
            self.sock.sendall(_frame(b"X", b""))
        except OSError:
            pass
        self.sock.close()


# -- fake server -------------------------------------------------------------

_INSERT_RE = re.compile(
    r"INSERT INTO (\w+) \(([^)]*)\) VALUES \(([^)]*)\)"
    r"(?: ON CONFLICT \(([^)]*)\) DO UPDATE SET .*)?$",
    re.DOTALL,
)
_DELETE_RE = re.compile(r"DELETE FROM (\w+) WHERE (.*)$", re.DOTALL)
_COND_RE = re.compile(r"(?:\w+\.)?(\w+)=\$(\d+)")


class FakePostgresServer:
    """Threaded in-process postgres: real v3 frames, in-memory tables,
    transaction staging (rows visible only after COMMIT)."""

    def __init__(
        self, password: str | None = None, auth: str | None = None
    ) -> None:
        self.password = password
        #: "trust" | "password" | "md5" | "scram-sha-256"
        self.auth = auth or ("password" if password is not None else "trust")
        #: table name -> list of row dicts (committed state)
        self.tables: dict[str, list[dict]] = {}
        #: every statement text the server executed, in order
        self.statements: list[str] = []
        #: frame tags seen, for protocol-shape assertions
        self.frames: list[str] = []
        self.commits = 0
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._closing = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- serving ------------------------------------------------------------

    def _serve(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            self._session(conn)
        except PgError:
            pass
        finally:
            conn.close()

    def _session(self, conn: socket.socket) -> None:
        reader = _FrameReader(conn)
        code, params_ = reader.read_startup()
        if code == _SSL_REQUEST:
            conn.sendall(b"N")  # SSL refused; client retries plaintext
            code, params_ = reader.read_startup()
        if code != _PROTOCOL_V3:
            raise PgError(f"unsupported protocol {code}")
        if not self._authenticate(conn, reader, params_.get("user", "")):
            return
        conn.sendall(
            _frame(b"R", struct.pack(">I", 0))
            + _frame(b"S", _cstr("server_version") + _cstr("16.0-fake"))
            + _frame(b"K", struct.pack(">II", 1234, 5678))
            + _frame(b"Z", b"I")
        )
        staged: list = []  # (table, op, payload) applied on COMMIT
        last_stmt = ""
        bound: list = []
        failed = False
        aborted = False  # statement error poisons the transaction
        while True:
            tag, body = reader.read_message()
            with self._lock:
                self.frames.append(tag.decode("ascii", "replace"))
            if tag == b"X":
                return
            if tag == b"Q":
                q = body.rstrip(b"\0").decode()
                with self._lock:
                    self.statements.append(q)
                word = q.split()[0].upper() if q.split() else ""
                if word == "BEGIN":
                    staged.clear()
                    aborted = False
                elif word == "COMMIT":
                    if aborted:
                        # real postgres: COMMIT of an aborted txn is a
                        # rollback (reported as such)
                        word = "ROLLBACK"
                    else:
                        self._apply(staged)
                        with self._lock:
                            self.commits += 1
                    staged.clear()
                    aborted = False
                elif word == "ROLLBACK":
                    staged.clear()
                    aborted = False
                else:
                    try:
                        self._run_sql(q, [], staged)
                    except PgError as exc:
                        conn.sendall(self._err(exc))
                        conn.sendall(_frame(b"Z", b"I"))
                        continue
                conn.sendall(
                    _frame(b"C", _cstr(word or "OK")) + _frame(b"Z", b"I")
                )
            elif tag == b"P":
                name_end = body.index(b"\0")
                rest = body[name_end + 1 :]
                q_end = rest.index(b"\0")
                last_stmt = rest[:q_end].decode()
                failed = False
                conn.sendall(_frame(b"1", b""))
            elif tag == b"B":
                i = body.index(b"\0") + 1  # portal name
                i += body[i:].index(b"\0") + 1  # statement name
                (nfmt,) = struct.unpack(">H", body[i : i + 2])
                i += 2 + 2 * nfmt
                (nparams,) = struct.unpack(">H", body[i : i + 2])
                i += 2
                params = []
                for _ in range(nparams):
                    (plen,) = struct.unpack(">i", body[i : i + 4])
                    i += 4
                    if plen < 0:
                        params.append(None)
                    else:
                        params.append(
                            decode_text_param(body[i : i + plen])
                        )
                        i += plen
                bound = params
                conn.sendall(_frame(b"2", b""))
            elif tag == b"D":
                conn.sendall(_frame(b"n", b""))
            elif tag == b"E":
                with self._lock:
                    self.statements.append(last_stmt)
                if aborted:
                    failed = True
                    conn.sendall(
                        self._err(
                            PgError(
                                "current transaction is aborted, commands "
                                "ignored until end of transaction block"
                            )
                        )
                    )
                    continue
                try:
                    self._run_sql(last_stmt, bound, staged)
                    conn.sendall(_frame(b"C", _cstr("INSERT 0 1")))
                except PgError as exc:
                    failed = True
                    aborted = True
                    conn.sendall(self._err(exc))
            elif tag == b"S":
                conn.sendall(_frame(b"Z", b"E" if failed else b"I"))
                failed = False
            else:
                raise PgError(f"unsupported frame {tag!r}")

    @staticmethod
    def _err(exc: PgError) -> bytes:
        return _frame(
            b"E", b"SERROR\0C42601\0M" + str(exc).encode() + b"\0\0"
        )

    def _auth_failed(self, conn: socket.socket) -> bool:
        conn.sendall(
            _frame(
                b"E",
                b"SFATAL\0C28P01\0Mpassword authentication failed\0\0",
            )
        )
        return False

    def _authenticate(
        self, conn: socket.socket, reader: _FrameReader, user: str
    ) -> bool:
        """Run the configured auth exchange; True = authenticated."""
        if self.auth == "trust":
            return True
        if self.auth == "password":
            conn.sendall(_frame(b"R", struct.pack(">I", 3)))
            tag, body = reader.read_message()
            if tag != b"p" or body.rstrip(b"\0").decode() != self.password:
                return self._auth_failed(conn)
            return True
        if self.auth == "md5":
            salt = os.urandom(4)
            conn.sendall(_frame(b"R", struct.pack(">I", 5) + salt))
            tag, body = reader.read_message()
            expected = _md5_password(user, self.password, salt)
            if tag != b"p" or body.rstrip(b"\0").decode() != expected:
                return self._auth_failed(conn)
            return True
        if self.auth == "scram-sha-256":
            conn.sendall(
                _frame(
                    b"R",
                    struct.pack(">I", 10) + _cstr("SCRAM-SHA-256") + b"\0",
                )
            )
            tag, body = reader.read_message()
            if tag != b"p":
                return self._auth_failed(conn)
            i = body.index(b"\0") + 1  # mechanism name
            (ilen,) = struct.unpack(">i", body[i : i + 4])
            client_first = body[i + 4 : i + 4 + ilen].decode()
            first_bare = client_first.split(",", 2)[2]
            client_nonce = dict(
                item.split("=", 1) for item in first_bare.split(",")
            )["r"]
            salt = os.urandom(16)
            iters = 4096
            full_nonce = (
                client_nonce + base64.b64encode(os.urandom(12)).decode()
            )
            server_first = (
                f"r={full_nonce},s={base64.b64encode(salt).decode()},"
                f"i={iters}"
            )
            conn.sendall(
                _frame(
                    b"R", struct.pack(">I", 11) + server_first.encode()
                )
            )
            tag, body = reader.read_message()
            if tag != b"p":
                return self._auth_failed(conn)
            client_final = body.decode()
            final_bare, proof_b64 = client_final.rsplit(",p=", 1)
            salted = _scram_salted_password(self.password, salt, iters)
            client_key = _hmac256(salted, b"Client Key")
            stored_key = hashlib.sha256(client_key).digest()
            auth_message = ",".join(
                (first_bare, server_first, final_bare)
            ).encode()
            signature = _hmac256(stored_key, auth_message)
            expected_proof = bytes(
                a ^ b for a, b in zip(client_key, signature)
            )
            if base64.b64decode(proof_b64) != expected_proof:
                return self._auth_failed(conn)
            server_sig = _hmac256(
                _hmac256(salted, b"Server Key"), auth_message
            )
            conn.sendall(
                _frame(
                    b"R",
                    struct.pack(">I", 12)
                    + b"v="
                    + base64.b64encode(server_sig),
                )
            )
            return True
        raise PgError(f"unknown auth mode {self.auth!r}")

    # -- statement interpretation -------------------------------------------

    def _run_sql(self, stmt: str, params: list, staged: list) -> None:
        def resolve(item: str) -> Any:
            item = item.strip()
            if item.startswith("$"):
                return params[int(item[1:]) - 1]
            return decode_text_param(item.encode())

        m = _INSERT_RE.match(stmt)
        if m is not None:
            table, cols, vals, conflict = m.groups()
            names = [c.strip() for c in cols.split(",")]
            values = [resolve(v) for v in vals.split(",")]
            if len(names) != len(values):
                raise PgError("column/value arity mismatch")
            row = dict(zip(names, values))
            keys = (
                [k.strip() for k in conflict.split(",")]
                if conflict
                else None
            )
            staged.append(("upsert" if keys else "insert", table, row, keys))
            return
        m = _DELETE_RE.match(stmt)
        if m is not None:
            table, conds = m.groups()
            pairs = _COND_RE.findall(conds)
            if not pairs:
                raise PgError(f"cannot parse DELETE condition {conds!r}")
            match = {
                name: params[int(idx) - 1] for name, idx in pairs
            }
            staged.append(("delete", table, match, None))
            return
        raise PgError(f"unsupported statement {stmt.split()[0]!r}")

    def _apply(self, staged: list) -> None:
        with self._lock:
            for op, table, payload, keys in staged:
                rows = self.tables.setdefault(table, [])
                if op == "insert":
                    rows.append(dict(payload))
                elif op == "upsert":
                    for row in rows:
                        if all(row.get(k) == payload[k] for k in keys):
                            row.update(payload)
                            break
                    else:
                        rows.append(dict(payload))
                else:  # delete
                    rows[:] = [
                        row
                        for row in rows
                        if not all(
                            row.get(k) == v for k, v in payload.items()
                        )
                    ]

    def snapshot(self, table: str) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self.tables.get(table, [])]
