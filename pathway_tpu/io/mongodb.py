"""pw.io.mongodb — write update streams into MongoDB
(reference: python/pathway/io/mongodb/__init__.py:14; documents carry
time/diff like BsonFormatter data_format.rs:1975)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import DocumentFormatter
from pathway_tpu.engine.storage import MongoWriter
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer, require


def write(
    table: Table,
    connection_string: str | None = None,
    database: str | None = None,
    collection: str | None = None,
    *,
    client: Any = None,
    **kwargs: Any,
) -> None:
    """Insert one document (row + time + diff) per change. ``client`` needs
    ``insert_many(collection, docs)``; pymongo adapts in two lines."""
    if client is None:
        pymongo = require("pymongo", "pw.io.mongodb")
        mongo = pymongo.MongoClient(connection_string)[database]

        class _Adapter:
            def insert_many(self, coll: str, docs: list) -> None:
                mongo[coll].insert_many(docs)

        client = _Adapter()

    def make_writer(column_names):
        return MongoWriter(client, collection, DocumentFormatter(column_names))

    attach_writer(table, make_writer)
