"""pw.io.mongodb — write update streams into MongoDB
(reference: python/pathway/io/mongodb/__init__.py:14; documents carry
time/diff like BsonFormatter data_format.rs:1975)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.formats import DocumentFormatter
from pathway_tpu.engine.storage import MongoWriter
from pathway_tpu.internals.table import Table
from pathway_tpu.io._utils import attach_writer


def write(
    table: Table,
    connection_string: str | None = None,
    database: str | None = None,
    collection: str | None = None,
    *,
    client: Any = None,
    **kwargs: Any,
) -> None:
    """Insert one document (row + time + diff) per change through the
    built-in wire client (``io/_mongo_wire.py``: own BSON codec + OP_MSG
    insert commands, one batch per commit). An injected ``client`` with
    ``insert_many(collection, docs)`` overrides it."""
    if client is None:
        from urllib.parse import urlparse

        from pathway_tpu.io._mongo_wire import MongoWireClient

        if connection_string is None or database is None:
            raise ValueError(
                "pw.io.mongodb needs connection_string and database "
                "(or client=)"
            )
        parsed = urlparse(
            connection_string
            if "://" in connection_string
            else f"mongodb://{connection_string}"
        )
        client = MongoWireClient(
            parsed.hostname or "127.0.0.1",
            parsed.port or 27017,
            database=database,
        )

    def make_writer(column_names):
        return MongoWriter(client, collection, DocumentFormatter(column_names))

    attach_writer(table, make_writer)
