"""MongoDB wire protocol: BSON codec + OP_MSG client + fake mongod.

The reference writes Mongo through a BSON formatter + client library
(BsonFormatter src/connectors/data_format.rs:1975); here the bytes
themselves are implemented:

- a from-scratch BSON encoder/decoder for the document types the
  DocumentFormatter emits (string/int64/double/bool/null/binary,
  nested documents and arrays) — element tags and little-endian layout
  per the BSON spec (bsonspec.org);
- the modern wire protocol: OP_MSG (opcode 2013) with a section-0
  command document, over the standard 16-byte message header
  (requestID/responseTo/opCode). ``insert`` commands carry the
  documents; ``hello`` performs the handshake.

The fake mongod accepts the same frames, decodes the BSON, applies
insert/find/count commands to in-memory collections, and replies with
real OP_MSG responses — so round-trip tests exercise genuine BSON on a
genuine wire.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any

_OP_MSG = 2013


class MongoError(Exception):
    """Command failure ({ok: 0}) or protocol violation."""


# -- BSON codec --------------------------------------------------------------


def _enc_cstring(s: str) -> bytes:
    return s.encode("utf-8") + b"\0"


def encode_bson(doc: dict) -> bytes:
    """dict -> BSON document bytes (spec: bsonspec.org)."""
    body = b""
    for key, value in doc.items():
        body += _encode_element(str(key), value)
    return struct.pack("<i", len(body) + 5) + body + b"\0"


def _encode_element(key: str, v: Any) -> bytes:
    name = _enc_cstring(key)
    if isinstance(v, bool):  # before int: bool subclasses int
        return b"\x08" + name + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(1 << 63) <= v < (1 << 63):
            return b"\x12" + name + struct.pack("<q", v)
        raise MongoError(f"int out of int64 range: {v}")
    if isinstance(v, float):
        return b"\x01" + name + struct.pack("<d", v)
    if isinstance(v, str):
        enc = v.encode("utf-8")
        return b"\x02" + name + struct.pack("<i", len(enc) + 1) + enc + b"\0"
    if v is None:
        return b"\x0a" + name
    if isinstance(v, (bytes, bytearray)):
        raw = bytes(v)
        return b"\x05" + name + struct.pack("<i", len(raw)) + b"\x00" + raw
    if isinstance(v, dict):
        return b"\x03" + name + encode_bson(v)
    if isinstance(v, (list, tuple)):
        as_doc = {str(i): item for i, item in enumerate(v)}
        return b"\x04" + name + encode_bson(as_doc)
    # exotic values (Json wrappers, pointers) stringify, like the
    # DocumentFormatter's fallback
    return _encode_element(key, str(v))


def decode_bson(data: bytes, offset: int = 0) -> tuple[dict, int]:
    """BSON document bytes -> (dict, end offset)."""
    (length,) = struct.unpack_from("<i", data, offset)
    end = offset + length
    pos = offset + 4
    out: dict = {}
    while pos < end - 1:
        tag = data[pos]
        pos += 1
        name_end = data.index(b"\0", pos)
        key = data[pos:name_end].decode("utf-8")
        pos = name_end + 1
        if tag == 0x01:
            (out[key],) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif tag == 0x02:
            (slen,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 4 : pos + 4 + slen - 1].decode("utf-8")
            pos += 4 + slen
        elif tag in (0x03, 0x04):
            sub, pos = decode_bson(data, pos)
            out[key] = (
                sub if tag == 0x03 else [sub[str(i)] for i in range(len(sub))]
            )
        elif tag == 0x05:
            (blen,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 5 : pos + 5 + blen]
            pos += 5 + blen
        elif tag == 0x08:
            out[key] = data[pos] == 1
            pos += 1
        elif tag == 0x0A:
            out[key] = None
        elif tag == 0x10:
            (out[key],) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif tag == 0x12:
            (out[key],) = struct.unpack_from("<q", data, pos)
            pos += 8
        else:
            raise MongoError(f"unsupported BSON tag 0x{tag:02x}")
    return out, end


# -- OP_MSG framing ----------------------------------------------------------


def _read_exact(sock: socket.socket, buf: bytearray, n: int) -> bytes:
    while len(buf) < n:
        chunk = sock.recv(65536)
        if not chunk:
            raise MongoError("connection closed by peer")
        buf += chunk
    out = bytes(buf[:n])
    del buf[:n]
    return out


def _read_message(sock: socket.socket, buf: bytearray) -> tuple[int, int, dict]:
    """One wire message -> (request_id, response_to, command document)."""
    header = _read_exact(sock, buf, 16)
    length, request_id, response_to, opcode = struct.unpack("<iiii", header)
    body = _read_exact(sock, buf, length - 16)
    if opcode != _OP_MSG:
        raise MongoError(f"unsupported opcode {opcode}")
    (_flags,) = struct.unpack_from("<I", body, 0)
    kind = body[4]
    if kind != 0:
        raise MongoError(f"unsupported OP_MSG section kind {kind}")
    doc, _end = decode_bson(body, 5)
    return request_id, response_to, doc


def _build_message(request_id: int, response_to: int, doc: dict) -> bytes:
    payload = struct.pack("<I", 0) + b"\x00" + encode_bson(doc)
    header = struct.pack(
        "<iiii", 16 + len(payload), request_id, response_to, _OP_MSG
    )
    return header + payload


class MongoWireClient:
    """``insert_many(collection, docs)`` over real OP_MSG frames (the
    MongoWriter client contract, engine/storage.py:422)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 27017,
        database: str = "pathway",
        connect_timeout: float = 10.0,
    ) -> None:
        self.database = database
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self._buf = bytearray()
        self._next_id = 1
        hello = self.command({"hello": 1, "$db": "admin"})
        self.server_info = hello
        self.sock.settimeout(None)

    def command(self, doc: dict) -> dict:
        rid = self._next_id
        self._next_id += 1
        self.sock.sendall(_build_message(rid, 0, doc))
        _req, response_to, reply = _read_message(self.sock, self._buf)
        if response_to != rid:
            raise MongoError(
                f"response_to {response_to} does not match request {rid}"
            )
        if not reply.get("ok"):
            raise MongoError(
                f"{reply.get('codeName', 'CommandFailed')}: "
                f"{reply.get('errmsg', reply)}"
            )
        return reply

    def insert_many(self, collection: str, docs: list) -> None:
        reply = self.command(
            {
                "insert": collection,
                "$db": self.database,
                "documents": [dict(d) for d in docs],
                "ordered": True,
            }
        )
        if reply.get("n") != len(docs):
            raise MongoError(
                f"insert acknowledged {reply.get('n')} of {len(docs)}"
            )

    def find(self, collection: str, filter_: dict | None = None) -> list[dict]:
        reply = self.command(
            {
                "find": collection,
                "$db": self.database,
                "filter": filter_ or {},
            }
        )
        return reply["cursor"]["firstBatch"]

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- fake mongod -------------------------------------------------------------


class FakeMongoServer:
    """In-process mongod: real OP_MSG frames, BSON decode, in-memory
    collections keyed '<db>.<collection>'."""

    def __init__(self) -> None:
        #: "db.collection" -> stored documents in arrival order
        self.collections: dict[str, list[dict]] = {}
        #: every command name the server decoded, in order
        self.commands: list[str] = []
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._closing = False
        threading.Thread(target=self._serve, daemon=True).start()

    def close(self) -> None:
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _serve(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        buf = bytearray()
        try:
            while True:
                request_id, _rt, doc = _read_message(conn, buf)
                reply = self._dispatch(doc)
                conn.sendall(
                    _build_message(10_000 + request_id, request_id, reply)
                )
        except (MongoError, OSError, ValueError):
            pass
        finally:
            conn.close()

    def _dispatch(self, doc: dict) -> dict:
        name = next(iter(doc), "")
        with self._lock:
            self.commands.append(name)
        if name == "hello":
            return {
                "ok": 1.0,
                "isWritablePrimary": True,
                "maxWireVersion": 17,
                "version": "7.0.0-fake",
            }
        db = doc.get("$db", "test")
        if name == "insert":
            key = f"{db}.{doc['insert']}"
            docs = doc.get("documents", [])
            with self._lock:
                self.collections.setdefault(key, []).extend(
                    dict(d) for d in docs
                )
            return {"ok": 1.0, "n": len(docs)}
        if name == "find":
            key = f"{db}.{doc['find']}"
            flt = doc.get("filter") or {}
            with self._lock:
                rows = [
                    d
                    for d in self.collections.get(key, ())
                    if all(d.get(k) == v for k, v in flt.items())
                ]
            return {
                "ok": 1.0,
                "cursor": {
                    "id": 0,
                    "ns": key,
                    "firstBatch": rows,
                },
            }
        if name == "count":
            key = f"{db}.{doc['count']}"
            with self._lock:
                n = len(self.collections.get(key, ()))
            return {"ok": 1.0, "n": n}
        return {
            "ok": 0.0,
            "errmsg": f"no such command: '{name}'",
            "codeName": "CommandNotFound",
        }

    def snapshot(self, namespace: str) -> list[dict]:
        with self._lock:
            return [dict(d) for d in self.collections.get(namespace, ())]
