"""Device-colocated collective exchange: repartition delta-batch columns
with XLA collectives instead of the host routing loop (ROADMAP item 2).

When a sharded mesh's workers are all backed by devices of ONE JAX mesh
(the in-process :class:`~pathway_tpu.engine.sharded.ShardedScheduler`, or
a single-process :class:`~pathway_tpu.engine.distributed.DistributedScheduler`
whose worker threads share the local device pool), the groupby/join/KNN
repartition does not need routing.py's D2H -> PWCF-encode -> TCP ->
decode -> H2D round-trip: the batch's raw bytes go on device ONCE, a
``shard_map`` + ``lax.all_to_all`` kernel moves every per-destination
bucket across the data axis, and each destination's rows come back as a
ready :class:`~pathway_tpu.engine.batch.Columns` — the ring-collective
idiom already used by ``pathway_tpu/parallel`` (ppermute/all-to-all over
a named axis, per the Ragged Paged Attention TPU-kernel discipline).

Mechanics (bit-exact by construction — the kernel only MOVES bytes):

1. **pack** — keys (16-byte digests), the optional diff vector, and every
   fixed-width column are viewed as raw little-endian bytes and
   concatenated into one ``(n_rows, row_bytes)`` uint8 payload matrix.
   Object/void columns cannot round-trip raw, so the batch *declines to
   host* (return ``None``, the caller runs the routing.py path) — the
   same "None IS the error channel" contract as ``columnar_shards``.
2. **bucket** — the host-side factorized shard codes (already computed by
   ``columnar_shards``) feed a device bucketing kernel: rows are split
   into ``n`` contiguous source chunks (one per device), and a stable
   argsort of ``(chunk, destination)`` builds per-chunk gather indices.
   Variable per-destination row counts are handled by count-exchange on
   host (the counts matrix rides along) + pad-to-max: bucket depth and
   chunk length pad to power-of-two buckets (:func:`device_ops.bucket_size`)
   so ragged batches reuse few compiled shapes.
3. **exchange** — ``parallel.sharding.shard_map_norep`` maps the kernel
   over the data axis of a :func:`parallel.mesh.make_mesh` mesh; each
   device gathers its ``(n, depth, row_bytes)`` send buffer locally and
   one ``lax.all_to_all`` swaps bucket ``d`` of every source to device
   ``d``.  Dispatch is split from fetch (PR-9 overlap discipline): the
   jitted call returns while XLA runs, the host prepares the trim
   offsets, and the single blocking fetch happens last.
4. **unpack** — per destination, the ``counts[s, d]``-trimmed buckets
   concatenate in source-chunk order; chunks are contiguous ascending
   row ranges, so the result row order equals the host path's
   ``np.flatnonzero(shards == d)`` order exactly — sinks are
   bit-identical with the collective on or off.

Control surface (the PR-2/PR-12 parity discipline):

- ``PATHWAY_TPU_COLLECTIVE_EXCHANGE=0`` — off; routing.py's host path is
  the bit-exact fallback spec and stays the only path.
- ``=1`` — force the collective wherever the payload is codeable and
  enough devices exist (CI runs this under the host-platform device sim).
- unset/auto — engage only when jax is already resident AND the default
  backend is a real accelerator; pure-host deployments pay one cached
  env check per delivery and nothing else.  The env is re-read per call,
  so the knob is live mid-run.

Placement is measurement-driven per edge (PR 12): a dedicated
:class:`~pathway_tpu.optimize.placement.PlacementPolicy` instance keyed
``("exchange", consumer_index)`` learns device-vs-host exchange ns/row
(EMA + hysteresis + periodic re-probe), so small batches keep the cheap
host path in auto mode; ``min_rows`` gates tiny commits outright.

Observability: ``pathway_collective_exchange_events_total{kind}``
(exchanges / declines / errors, :data:`COLLECTIVE_STATS` is the
authoritative alias dict), ``pathway_collective_exchange_ns_total`` and
``pathway_collective_exchange_bytes_total`` counters, plus PR-8 tracing:
host pack/unpack time lands in the critical path's ``exchange`` bucket
(``collective-pack`` / ``collective-unpack`` spans) and the device wall
is recorded via :func:`device_ops.record_kernel`
(``collective_exchange.all_to_all``) so it lands in the ``device``
bucket — no wall second is counted twice.

PR-4 composition: elided edges never reach this module — both schedulers
check the elision set before any routing (or collective) work.  PR-6
composition: an exchange that fails mid-flight performs NO pushes and
returns ``None``, so the caller's host path delivers the whole batch;
recovery/rollback never observes a half-delivered collective.
"""

from __future__ import annotations

import os
import sys
import threading
import time as _time
from typing import TYPE_CHECKING, Any

import numpy as np

from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing

if TYPE_CHECKING:  # pragma: no cover
    from pathway_tpu.engine.batch import Columns

__all__ = [
    "COLLECTIVE_STATS",
    "EXCHANGE_POLICY",
    "enabled",
    "exchange",
    "forced",
    "mesh_ready",
    "min_rows",
    "record_host",
    "stats",
    "tracking",
]

_LOCK = threading.Lock()

#: collective-path probe counters; the dict is the authoritative alias
#: (same discipline as routing.EXCHANGE_STATS), mirrored into the
#: ``pathway_collective_exchange_events_total{kind=...}`` family.
COLLECTIVE_STATS = _metrics.MirroredCounterDict(
    "pathway_collective_exchange_events_total",
    "kind",
    {
        "exchanges": 0,            # batches repartitioned on device
        "declined_non_codeable": 0,  # object/void column -> host path
        "errors": 0,               # device call raised -> host path
    },
    help="collective exchange events by kind (mirrors COLLECTIVE_STATS)",
)

_C_NS = _metrics.REGISTRY.counter(
    "pathway_collective_exchange_ns_total",
    "total wall ns spent in collective exchanges (pack+kernel+unpack)",
)
_C_BYTES = _metrics.REGISTRY.counter(
    "pathway_collective_exchange_bytes_total",
    "payload bytes repartitioned through the device collective",
)

_JAX_OK: bool | None = None
_BACKEND: str | None | bool = False  # False = not probed yet
_ENABLED_CACHE: tuple[str, bool] | None = None
_DEVICES_OK: dict[int, bool] = {}  # guarded-by: _LOCK — n_shards -> enough devices
_MESH_CACHE: dict[int, Any] = {}  # guarded-by: _LOCK — n_shards -> jax Mesh
_KERNEL_CACHE: dict[int, Any] = {}  # guarded-by: _LOCK — n_shards -> jitted all_to_all


def _jax_ok() -> bool:
    """jax importable (cached) — never raises."""
    global _JAX_OK
    if _JAX_OK is None:
        try:
            import jax  # noqa: F401
            import jax.numpy  # noqa: F401

            _JAX_OK = True
        except Exception:
            _JAX_OK = False
    return _JAX_OK


def _default_backend() -> str | None:
    global _BACKEND
    if _BACKEND is False:
        try:
            import jax

            _BACKEND = jax.default_backend()
        except Exception:
            _BACKEND = None
    return _BACKEND


def enabled() -> bool:
    """Whether the collective path may engage at all (env contract above).

    Cached per raw env value — the delivery hot path calls this once per
    batch, so the auto probe (backend detection) runs at most once, and
    flipping ``PATHWAY_TPU_COLLECTIVE_EXCHANGE`` mid-run takes effect on
    the next delivery."""
    global _ENABLED_CACHE
    raw = os.environ.get(
        "PATHWAY_TPU_COLLECTIVE_EXCHANGE", ""
    ).strip().lower()
    cached = _ENABLED_CACHE
    if cached is not None and cached[0] == raw:
        return cached[1]
    if raw in ("0", "false", "off", "no"):
        val = False
    elif raw in ("1", "true", "on", "yes", "force"):
        val = _jax_ok()
    else:
        # auto: only with jax already resident AND a real accelerator —
        # never silently re-route host exchanges through jax-on-CPU
        val = (
            "jax" in sys.modules
            and _jax_ok()
            and _default_backend() not in (None, "cpu")
        )
    _ENABLED_CACHE = (raw, val)
    return val


def forced() -> bool:
    """True when ``PATHWAY_TPU_COLLECTIVE_EXCHANGE=1`` pins eligible
    repartitions to the collective (parity CI); the per-edge policy then
    skips measurement-driven arbitration and the ``min_rows`` gate."""
    raw = os.environ.get(
        "PATHWAY_TPU_COLLECTIVE_EXCHANGE", ""
    ).strip().lower()
    return raw in ("1", "true", "on", "yes", "force") and enabled()


def mesh_ready(n_shards: int) -> bool:
    """Mesh-detection rule: the collective needs one device per worker
    shard (host-platform device sim counts — CI forces 4/8 CPU devices).
    Cached per shard count; never raises."""
    if n_shards < 2:
        return False
    with _LOCK:
        cached = _DEVICES_OK.get(n_shards)
    if cached is None:
        from pathway_tpu.engine.device import device_count

        cached = device_count() >= n_shards
        with _LOCK:
            _DEVICES_OK[n_shards] = cached
    return cached


def min_rows() -> int:
    """Batches below this row count keep the host path in auto mode —
    collective dispatch latency dominates tiny commits (forced mode
    ignores this so CI exercises the kernel on toy batches)."""
    try:
        return max(
            0,
            int(
                os.environ.get("PATHWAY_TPU_COLLECTIVE_MIN_ROWS", "512")
            ),
        )
    except ValueError:
        return 512


def _policy():
    from pathway_tpu.optimize.placement import PlacementPolicy

    return PlacementPolicy(
        enabled_fn=enabled, forced_fn=forced, min_rows_fn=min_rows
    )


#: per-edge device-vs-host exchange cost arbiter (PR-12 machinery with
#: this module's gates): keyed ("exchange", consumer index), EMA ns/row
#: per side, hysteresis + re-probe — small batches keep the host path.
EXCHANGE_POLICY = None  # created lazily; placement imports stay off the cold path


def _exchange_policy():
    global EXCHANGE_POLICY
    if EXCHANGE_POLICY is None:
        EXCHANGE_POLICY = _policy()
    return EXCHANGE_POLICY


def tracking(n_shards: int) -> bool:
    """True when the caller should time its host split and feed
    :func:`record_host` — i.e. the collective is live for this mesh and
    the per-edge policy is comparing sides."""
    return enabled() and mesh_ready(n_shards)


def record_host(edge: int, n_rows: int, ns: int) -> None:
    """Fold one observed host-path repartition into the per-edge EMA."""
    _exchange_policy().record("exchange", edge, False, n_rows, ns)


# -- payload packing ----------------------------------------------------------


def _as_bytes(arr: np.ndarray, width: int) -> np.ndarray:
    """(n, width) raw-byte view of a contiguous fixed-width 1-D array."""
    arr = np.ascontiguousarray(arr)
    try:
        return arr.view(np.uint8).reshape(len(arr), width)
    except (TypeError, ValueError):
        return np.frombuffer(arr.tobytes(), np.uint8).reshape(
            len(arr), width
        )


def _pack_payload(columns: "Columns"):
    """Concatenate keys | diffs | columns into one ``(n, W)`` uint8
    payload matrix.  Returns ``(payload, layout, has_diffs)`` or
    ``(None, None, False)`` when any column cannot round-trip raw
    (object/void dtype) or key derivation fails — the decline channel."""
    n = columns.n
    try:
        kb = np.ascontiguousarray(columns.kbytes(), np.uint8)
    except Exception:
        return None, None, False
    segs = [kb.reshape(n, 16)]
    has_diffs = columns.diffs is not None
    if has_diffs:
        segs.append(
            _as_bytes(np.ascontiguousarray(columns.diffs, np.int64), 8)
        )
    layout: list[tuple] = []
    for col in columns.cols:
        if col.dtype.kind in "OV":
            return None, None, False
        width = col.dtype.itemsize
        segs.append(_as_bytes(col, width))
        layout.append((col.dtype, width))
    return np.concatenate(segs, axis=1), layout, has_diffs


def _unpack_rows(
    rows: np.ndarray, layout: list, has_diffs: bool
) -> "Columns":
    """Inverse of :func:`_pack_payload` for one destination's row block."""
    from pathway_tpu.engine.batch import Columns

    m = len(rows)
    kb = np.ascontiguousarray(rows[:, :16])
    off = 16
    diffs = None
    if has_diffs:
        diffs = (
            np.ascontiguousarray(rows[:, off : off + 8])
            .view(np.int64)
            .ravel()
        )
        off += 8
    cols = []
    for dtype, width in layout:
        seg = np.ascontiguousarray(rows[:, off : off + width])
        cols.append(seg.view(dtype).ravel())
        off += width
    return Columns(m, cols, kbytes=kb, diffs=diffs)


# -- the device kernel --------------------------------------------------------


def _mesh(n: int):
    with _LOCK:
        mesh = _MESH_CACHE.get(n)
    if mesh is None:
        import jax

        from pathway_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(data=n, devices=jax.devices()[:n])
        with _LOCK:
            _MESH_CACHE[n] = mesh
    return mesh


def _kernel(n: int):
    """The jitted bucketing + all-to-all kernel for an ``n``-way mesh.

    Per device: gather the local chunk's per-destination send buffer
    ``(n, depth, W)`` from the host-built index matrix, then one
    ``lax.all_to_all`` over the data axis delivers bucket ``d`` of every
    source chunk to device ``d``.  Cached per worker count; jit re-specializes
    per (chunk, depth, W) shape — all three pad to power-of-two buckets so
    ragged batches reuse few compiled shapes."""
    with _LOCK:
        fn = _KERNEL_CACHE.get(n)
    if fn is not None:
        return fn
    import jax
    from jax import lax

    from jax.sharding import PartitionSpec as P

    from pathway_tpu.parallel.mesh import DATA_AXIS
    from pathway_tpu.parallel.sharding import shard_map_norep

    def bucket_and_swap(payload, gidx):
        # payload: (chunk, W) local rows; gidx: (1, n, depth) local indices
        send = payload[gidx[0]]  # (n, depth, W) per-destination buckets
        return lax.all_to_all(
            send, DATA_AXIS, split_axis=0, concat_axis=0
        )

    fn = jax.jit(
        shard_map_norep(
            bucket_and_swap,
            mesh=_mesh(n),
            in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
        )
    )
    with _LOCK:
        _KERNEL_CACHE[n] = fn
    return fn


def exchange(
    edge: int,
    columns: "Columns",
    shards: np.ndarray,
    n: int,
    consumer=None,
) -> "list[Columns | None] | None":
    """Repartition ``columns`` by the precomputed ``shards`` vector over
    an ``n``-device collective.  Returns one :class:`Columns` per
    destination (``None`` where a destination receives no rows), or
    ``None`` to DECLINE — non-codeable payload, mesh not ready, policy
    chose host, or a device error — in which case the caller runs the
    host path and NO pushes have happened (the PR-6 rollback seam).

    The device-residency plane hooks both ends of this call
    (``engine/device_residency.py``):

    - **ingress** — a still-resident :class:`DeviceResidentColumns`
      input re-packs from its device rows (the wire layout IS the
      resident layout), skipping the host payload upload entirely;
    - **egress** — when ``consumer`` is a device-placed eligible
      operator (``consumer_resident_ok``), the all-to-all output is
      trimmed per destination ON DEVICE and delivered as resident
      batches instead of fetching the whole padded buffer; any failure
      in that trim falls back to the whole-buffer host fetch before a
      single push happens, so the fallback is a clean mode switch.

    Host<->device transfers are counted in BOTH modes
    (``pathway_device_transfer_*``) so a residency-on run is directly
    comparable against its own residency-off baseline."""
    n_rows = columns.n
    if n_rows == 0 or not enabled() or not mesh_ready(n):
        return None
    if not _exchange_policy().choose("exchange", edge, n_rows):
        return None
    from pathway_tpu.engine import device_ops as _device_ops
    from pathway_tpu.engine import device_residency as _dres

    trace = _tracing.current()
    t0 = _time.perf_counter()
    # zero-copy ingress: a still-resident device batch already holds the
    # packed keys|diffs|cols wire rows on device — reuse them and skip
    # the host marshalling + payload upload
    dev_payload = None
    payload = None
    if isinstance(columns, _dres.DeviceResidentColumns):
        dev_payload = columns.device_rows()
    if dev_payload is not None:
        layout = columns.layout
        has_diffs = columns.has_diffs
        width = 16 + (8 if has_diffs else 0) + sum(
            w for _dt, w in layout
        )
        payload_nbytes = n_rows * width
    else:
        payload, layout, has_diffs = _pack_payload(columns)
        if payload is None:
            COLLECTIVE_STATS["declined_non_codeable"] += 1
            return None
        width = payload.shape[1]
        payload_nbytes = int(payload.nbytes)
    p1 = _time.perf_counter()
    if trace is not None:
        # the exchange-bucket span covers ONLY the byte marshalling —
        # the analog of the host path's pwcf-encode span; the bucketing
        # math below is routing work (what columnar_shards/gather-split
        # do on the host path) and stays in the host-compute residual,
        # so the two paths' critical-path buckets compare like-for-like
        trace.span(
            "collective-pack",
            "exchange",
            t0,
            p1,
            rows=n_rows,
            bytes=payload_nbytes,
            edge=edge,
        )
    # contiguous source chunks, padded to a power-of-two length so the
    # jitted kernel re-specializes on few shapes (Ragged Paged Attention
    # discipline via device_ops.bucket_size)
    chunk = _device_ops.bucket_size(-(-n_rows // n))
    row_chunk = np.arange(n_rows, dtype=np.int64) // chunk
    shards64 = shards.astype(np.int64, copy=False)
    group = row_chunk * n + shards64  # per-row (chunk, destination) code
    counts = np.bincount(group, minlength=n * n).reshape(n, n)
    depth = _device_ops.bucket_size(int(counts.max()))
    # stable argsort groups rows by (chunk, destination) with ascending
    # original index inside each group — the exact order the host path's
    # np.flatnonzero(shards == d) produces per destination
    order = np.argsort(group, kind="stable")
    sorted_group = group[order]
    starts = np.zeros(n * n + 1, np.int64)
    np.cumsum(counts.ravel(), out=starts[1:])
    gidx = np.zeros((n * n, depth), np.int32)
    gidx[sorted_group, np.arange(n_rows) - starts[sorted_group]] = (
        order % chunk
    ).astype(np.int32)
    resident_out = False
    try:
        k0 = _time.perf_counter()
        if dev_payload is not None:
            import jax.numpy as jnp

            padded_in = jnp.zeros((n * chunk, width), jnp.uint8)
            padded_in = padded_in.at[:n_rows].set(dev_payload)
            _dres.record_h2d(gidx.nbytes)  # only the index matrix crosses
            _dres.record_saved(payload_nbytes)
            _dres.RESIDENCY_STATS["device_consumes"] += 1
        else:
            padded = np.zeros((n * chunk, width), np.uint8)
            padded[:n_rows] = payload
            padded_in = padded
            _dres.record_h2d(padded.nbytes + gidx.nbytes)
        # dispatch, then overlap: jax returns while XLA bucket-gathers and
        # swaps; the host meanwhile derives the per-destination trim sizes,
        # and the blocking fetch (when one happens at all) comes last —
        # the PR-9 dispatch/fetch overlap discipline
        out_dev = _kernel(n)(padded_in, gidx.reshape(n, n, depth))
        dest_counts = counts.sum(axis=0)
        resident_out = _dres.consumer_resident_ok(consumer)
        fetched = None
        if not resident_out:
            fetched = np.asarray(out_dev)
            _dres.record_d2h(fetched.nbytes)
        k1 = _time.perf_counter()
    except Exception:
        COLLECTIVE_STATS["errors"] += 1
        return None
    _device_ops.record_kernel(
        "collective_exchange.all_to_all", int((k1 - k0) * 1e9)
    )
    parts: list = [None] * n
    if resident_out:
        seam_key = _dres.consumer_seam_key(consumer)
        try:
            import jax.numpy as jnp

            trimmed_bytes = 0
            for d in range(n):
                m = int(dest_counts[d])
                if m == 0:
                    continue
                block = out_dev[d * n : (d + 1) * n]
                rows_dev = jnp.concatenate(
                    [block[s, : int(counts[s, d])] for s in range(n)],
                    axis=0,
                )
                parts[d] = _dres.DeviceResidentColumns.from_device_rows(
                    rows_dev, layout, has_diffs, seam_key=seam_key
                )
                trimmed_bytes += m * width
            # the padded tail of the all-to-all buffer never crosses to
            # host in resident mode — that is the guaranteed net saving
            # even if every part later materializes
            _dres.record_saved(int(out_dev.nbytes) - trimmed_bytes)
        except Exception:
            # resident egress failed — fetch the whole buffer and run
            # the host decode; nothing was pushed yet, so this is a
            # clean fallback, not a partial delivery
            _dres.RESIDENCY_STATS["declines"] += 1
            parts = [None] * n
            resident_out = False
            try:
                fetched = np.asarray(out_dev)
                _dres.record_d2h(fetched.nbytes)
            except Exception:
                COLLECTIVE_STATS["errors"] += 1
                return None
    if not resident_out:
        for d in range(n):
            m = int(dest_counts[d])
            if m == 0:
                continue
            block = fetched[d * n : (d + 1) * n]
            rows = np.concatenate(
                [block[s, : counts[s, d]] for s in range(n)], axis=0
            )
            parts[d] = _unpack_rows(rows, layout, has_diffs)
    t1 = _time.perf_counter()
    if trace is not None:
        trace.span(
            "collective-unpack",
            "exchange",
            k1,
            t1,
            rows=n_rows,
            edge=edge,
            resident=bool(resident_out),
        )
    total_ns = int((t1 - t0) * 1e9)
    COLLECTIVE_STATS["exchanges"] += 1
    _C_NS.inc(total_ns)
    _C_BYTES.inc(float(payload_nbytes))
    _exchange_policy().record("exchange", edge, True, n_rows, total_ns)
    return parts


def stats() -> dict:
    """Structured roll-up for bench JSON / cli stats."""
    return {
        "enabled": enabled(),
        "forced": forced(),
        "events": dict(COLLECTIVE_STATS),
        "ns_total": int(_C_NS.value),
        "bytes_total": int(_C_BYTES.value),
        "placement": _exchange_policy().decisions(),
    }


def reset_counters() -> None:
    """Test/bench helper: zero the event counters and the per-edge policy."""
    for key in list(COLLECTIVE_STATS):
        COLLECTIVE_STATS[key] = 0
    _C_NS.value = 0.0
    _C_BYTES.value = 0.0
    _exchange_policy().reset()
