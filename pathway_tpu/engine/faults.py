"""Deterministic fault injection for the exchange mesh.

A :class:`FaultPlan` is loaded from ``PATHWAY_TPU_FAULT_PLAN`` — either
inline JSON or a path to a JSON file — and injects failures at the two
seams the fault-tolerance layer defends:

- ``on_commit(process_id, time)`` fires AFTER a commit's operator
  snapshot is written (the clean recovery boundary).  A matching
  ``kill`` fault SIGKILLs the process — indistinguishable from an OOM
  kill or a machine loss from the mesh's point of view.
- ``on_send(process_id, peer, frame)`` fires before every mesh frame is
  written to the socket.  ``drop`` swallows the frame, ``delay`` sleeps
  before sending, ``dup`` sends it twice, ``reset`` hard-closes the
  socket mid-stream (a synthetic RST).

Plan format (JSON object)::

    {"seed": 7,
     "faults": [
       {"type": "kill",  "process": 1, "at_commit": 3},
       {"type": "drop",  "process": 1, "peer": 0, "kind": "hb",
        "count": 2},
       {"type": "delay", "process": 2, "kind": "round", "count": 3,
        "ms": 50},
       {"type": "dup",   "process": 1, "kind": "round", "count": 1},
       {"type": "reset", "process": 1, "peer": 0, "after_sends": 10}
     ]}

Selectors: ``process`` (required — which worker the fault lives in;
``"*"``, ``"all"`` or ``-1`` match every worker, so a wildcard ``kill``
at one commit is a total-mesh kill — the cold-restart scenario),
``peer`` (optional — only frames bound for that peer), ``kind``
(optional — only frames whose tuple tag matches, e.g. ``"round"``,
``"hb"``, ``"cmd"``), ``count`` (how many frames to affect; default 1),
``at_commit`` (kill boundary), ``after_sends`` (matching sends to let
through before a reset fires).  Jitter drawn inside the plan uses
``random.Random(seed)`` so a plan replays identically.

The plan is a lazy module singleton: when ``PATHWAY_TPU_FAULT_PLAN`` is
unset the hot-path cost is one ``None`` check per send.
"""

from __future__ import annotations

import json
import os
import random
import signal
import time as _walltime
from typing import Any


class _Fault:
    __slots__ = (
        "type", "process", "peer", "kind", "count", "at_commit",
        "after_sends", "ms", "_sends_seen",
    )

    def __init__(self, spec: dict) -> None:
        self.type = spec["type"]
        if self.type not in ("kill", "drop", "delay", "dup", "reset"):
            raise ValueError(f"unknown fault type {self.type!r}")
        # process "*" / "all" / -1 matches every worker — the total-kill
        # spelling used by the cold-restart scenario (a kill fault with a
        # wildcard process takes the whole mesh down at one commit)
        proc = spec["process"]
        if proc in ("*", "all"):
            self.process = -1
        else:
            self.process = int(proc)
        self.peer = spec.get("peer")
        self.kind = spec.get("kind")
        self.count = int(spec.get("count", 1))
        self.at_commit = spec.get("at_commit")
        self.after_sends = int(spec.get("after_sends", 0))
        self.ms = float(spec.get("ms", 0.0))
        self._sends_seen = 0

    def matches_process(self, process_id: int) -> bool:
        return self.process == -1 or self.process == process_id

    def matches_frame(self, peer: int, frame: Any) -> bool:
        if self.count <= 0:
            return False
        if self.peer is not None and int(self.peer) != peer:
            return False
        if self.kind is not None:
            tag = frame[0] if isinstance(frame, tuple) and frame else None
            if tag != self.kind:
                return False
        if self.after_sends:
            self._sends_seen += 1
            if self._sends_seen <= self.after_sends:
                return False
        return True


class FaultPlan:
    """Parsed fault plan; see module docstring for the JSON format."""

    def __init__(self, spec: dict) -> None:
        self.seed = int(spec.get("seed", 0))
        self.rng = random.Random(self.seed)
        self.faults = [_Fault(f) for f in spec.get("faults", [])]
        # a restarted worker re-parses the same plan, so without credit
        # its kill fault would fire again on every incarnation — the
        # supervisor stamps how many restarts this slot has had, and we
        # treat that many kill firings as already consumed
        try:
            self._kill_credit = int(
                os.environ.get("PATHWAY_TPU_RESTART_COUNT", "0")
            )
        except ValueError:
            self._kill_credit = 0

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        raw = os.environ.get("PATHWAY_TPU_FAULT_PLAN")
        if not raw:
            return None
        raw = raw.strip()
        if not raw.startswith("{"):
            with open(raw, "r", encoding="utf-8") as fh:
                raw = fh.read()
        try:
            spec = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"PATHWAY_TPU_FAULT_PLAN is not valid JSON: {exc}"
            ) from exc
        return cls(spec)

    # -- injection seams -----------------------------------------------------

    def on_commit(self, process_id: int, time: int) -> None:
        """Called after the commit-boundary snapshot write.  A matching
        ``kill`` fault SIGKILLs this worker — the snapshot for ``time``
        is durable, everything after it is lost."""
        for f in self.faults:
            if (
                f.type == "kill"
                and f.matches_process(process_id)
                and f.at_commit is not None
                and time >= int(f.at_commit)
                and f.count > 0
            ):
                f.count -= 1
                if self._kill_credit > 0:
                    self._kill_credit -= 1
                    continue  # fired in a previous incarnation
                from pathway_tpu.internals.metrics import FLIGHT

                FLIGHT.record(
                    "fault_kill", process=process_id, time=time
                )
                FLIGHT.dump("fault-injected kill")
                os.kill(os.getpid(), signal.SIGKILL)

    def on_send(self, process_id: int, peer: int, frame: Any) -> str:
        """Consulted by ``MeshTransport.send``.  Returns the action for
        this frame: ``"send"`` (default), ``"drop"``, ``"dup"``, or
        ``"reset"``; a ``delay`` fault sleeps here and then sends."""
        for f in self.faults:
            if not f.matches_process(process_id) or f.type == "kill":
                continue
            if not f.matches_frame(peer, frame):
                continue
            f.count -= 1
            if f.type == "delay":
                # deterministic jitter: up to 20% around the nominal delay
                ms = f.ms * (0.9 + 0.2 * self.rng.random())
                _walltime.sleep(ms / 1000.0)
                return "send"
            return f.type
        return "send"


_PLAN: FaultPlan | None = None
_LOADED = False


def active_plan() -> FaultPlan | None:
    """The process-wide plan (lazily parsed from the environment)."""
    global _PLAN, _LOADED
    if not _LOADED:
        _PLAN = FaultPlan.from_env()
        _LOADED = True
    return _PLAN


def reset_plan() -> None:
    """Forget the cached plan (tests that mutate the env call this)."""
    global _PLAN, _LOADED
    _PLAN = None
    _LOADED = False
