"""Device-resident delta batches: the zero-copy plane between device
operators and the collective exchange (ROADMAP item 2, closing note).

PR 12 put the hot stateful operators on device and PR 16 put the
repartition exchange on device, but the two planes still handed off
through host NumPy: a device groupby feeding a device join paid
D2H -> H2D on *both* sides of every exchange.  This module closes that
seam with a :class:`DeviceResidentColumns` — a
:class:`~pathway_tpu.engine.batch.Columns` whose packed payload bytes
(keys | diffs | fixed-width columns, the exact
``collective_exchange._pack_payload`` wire layout) stay on device, while
the host side keeps only the schema/factorization metadata that cannot
live on device (row count, per-column dtypes/widths, the eagerly fetched
diff vector the delivery path must inspect).

Residency is TRANSPARENT: any host access (``cols``, ``kbytes()``,
``gather`` …) materializes the batch bit-exactly through the same
``_unpack_rows`` spec the collective's host path uses, so a consumer
that cannot (or chooses not to) consume device buffers simply pays the
one trimmed D2H it would have paid anyway — there is no partial-push
failure mode, preserving the PR-6 rollback invariant.  A consumer that
CAN consume device-side (the PR-12 join matcher over int64 key codes,
the exchange packing a still-resident batch back out) reads
:meth:`DeviceResidentColumns.device_column` /
:meth:`DeviceResidentColumns.device_rows` and skips the transfer
entirely.

Control surface (the PR-2/PR-12/PR-16 parity discipline):

- ``PATHWAY_TPU_DEVICE_RESIDENCY=0`` — off; every collective exchange
  output materializes to host immediately (the bit-exact fallback spec).
- ``=1`` — force residency wherever the exchange engaged and the
  consumer is a device-eligible operator (CI runs this under the
  host-platform device sim).
- unset/auto — engage only when jax is already resident AND the default
  backend is a real accelerator; additionally the consumer's measured
  placement (:mod:`pathway_tpu.optimize.placement`) must currently have
  the operator on device.  The env is re-read per call, so the knob is
  live mid-run.

Any decline — object columns, non-codeable keys, a device error while
trimming — falls back to the host materialization with NO partial
pushes: the exchange's device output is either delivered whole as
resident parts or fetched whole as host parts.

Lifecycle (the drain-before-persistence exactly-once seam): live
resident batches register in a WeakSet (the
``device.decay_device_batches`` idiom);
:func:`decay_resident_batches` — called from
``device_pipeline.commit_boundary``/``drain``/``drain_until`` —
materializes any survivor and drops its device buffer, so HBM stays
bounded by one commit and a checkpoint for commit N only ever snapshots
host-resident state.

Observability: ``pathway_device_transfer_{h2d,d2h}_{events,bytes}_total``
count every host<->device crossing this plane performs (both modes, so a
residency-on run is comparable against its own baseline),
``pathway_device_residency_bytes_saved_total`` counts bytes that did NOT
cross because a buffer stayed resident, and
``pathway_device_residency_events_total{kind}``
(:data:`RESIDENCY_STATS`) counts resident batches, materializations,
device-side consumes, and declines.  Materialization wall lands in the
tracing ``exchange`` bucket (``residency-materialize`` span) and feeds
the consumer's seam EMA for chain-aware placement
(``PlacementPolicy.record_seam``).
"""

from __future__ import annotations

import os
import sys
import time as _time
import weakref

import numpy as np

from pathway_tpu.engine.batch import Columns
from pathway_tpu.internals import metrics as _metrics
from pathway_tpu.internals import tracing as _tracing

__all__ = [
    "DeviceResidentColumns",
    "RESIDENCY_STATS",
    "consumer_resident_ok",
    "consumer_seam_key",
    "decay_resident_batches",
    "enabled",
    "forced",
    "record_d2h",
    "record_h2d",
    "record_saved",
    "reset_counters",
    "stats",
]

#: residency-plane probe counters; the dict is the authoritative alias
#: (same discipline as routing.EXCHANGE_STATS), mirrored into the
#: ``pathway_device_residency_events_total{kind=...}`` family.
RESIDENCY_STATS = _metrics.MirroredCounterDict(
    "pathway_device_residency_events_total",
    "kind",
    {
        "resident_batches": 0,   # batches kept device-resident at a seam
        "materializations": 0,   # resident batches fetched to host
        "device_consumes": 0,    # device buffers consumed transfer-free
        "declines": 0,           # residency attempted, fell back to host
    },
    help="device-residency events by kind (mirrors RESIDENCY_STATS)",
)

_H2D_EVENTS = _metrics.REGISTRY.counter(
    "pathway_device_transfer_h2d_events_total",
    "host->device transfers performed by the delta-batch plane",
)
_H2D_BYTES = _metrics.REGISTRY.counter(
    "pathway_device_transfer_h2d_bytes_total",
    "host->device bytes moved by the delta-batch plane",
)
_D2H_EVENTS = _metrics.REGISTRY.counter(
    "pathway_device_transfer_d2h_events_total",
    "device->host transfers performed by the delta-batch plane",
)
_D2H_BYTES = _metrics.REGISTRY.counter(
    "pathway_device_transfer_d2h_bytes_total",
    "device->host bytes moved by the delta-batch plane",
)
_SAVED_BYTES = _metrics.REGISTRY.counter(
    "pathway_device_residency_bytes_saved_total",
    "bytes that stayed device-resident instead of crossing the seam",
)

_JAX_OK: bool | None = None
_BACKEND: str | None | bool = False  # False = not probed yet
_ENABLED_CACHE: tuple[str, bool] | None = None

#: this commit's live resident batches (the device._LIVE_HANDLES idiom);
#: decay_resident_batches() materializes survivors at commit boundaries
_LIVE_RESIDENT: "weakref.WeakSet" = weakref.WeakSet()


def _jax_ok() -> bool:
    """jax importable (cached) — never raises."""
    global _JAX_OK
    if _JAX_OK is None:
        try:
            import jax  # noqa: F401
            import jax.numpy  # noqa: F401

            _JAX_OK = True
        except Exception:
            _JAX_OK = False
    return _JAX_OK


def _default_backend() -> str | None:
    global _BACKEND
    if _BACKEND is False:
        try:
            import jax

            _BACKEND = jax.default_backend()
        except Exception:
            _BACKEND = None
    return _BACKEND


def enabled() -> bool:
    """Whether exchange outputs may stay device-resident at all (env
    contract above).  Cached per raw env value — the delivery hot path
    calls this once per batch, so the auto probe runs at most once, and
    flipping ``PATHWAY_TPU_DEVICE_RESIDENCY`` mid-run takes effect on
    the next delivery."""
    global _ENABLED_CACHE
    raw = os.environ.get(
        "PATHWAY_TPU_DEVICE_RESIDENCY", ""
    ).strip().lower()
    cached = _ENABLED_CACHE
    if cached is not None and cached[0] == raw:
        return cached[1]
    if raw in ("0", "false", "off", "no"):
        val = False
    elif raw in ("1", "true", "on", "yes", "force"):
        val = _jax_ok()
    else:
        # auto: only with jax already resident AND a real accelerator —
        # keeping buffers on a jax-CPU "device" saves nothing
        val = (
            "jax" in sys.modules
            and _jax_ok()
            and _default_backend() not in (None, "cpu")
        )
    _ENABLED_CACHE = (raw, val)
    return val


def forced() -> bool:
    """True when ``PATHWAY_TPU_DEVICE_RESIDENCY=1`` pins every eligible
    exchange output resident (parity CI); auto mode instead consults the
    consumer's measured placement."""
    raw = os.environ.get(
        "PATHWAY_TPU_DEVICE_RESIDENCY", ""
    ).strip().lower()
    return raw in ("1", "true", "on", "yes", "force") and enabled()


def consumer_seam_key(consumer) -> "tuple | None":
    """The placement key a delivery to ``consumer`` belongs to: the
    consumer itself when it is an annotated device-eligible operator,
    else the downstream eligible operator the placement pass marked it
    as feeding (repartitions often land on the row-local
    expression/filter stage directly above the stateful operator), else
    ``None``."""
    if consumer is None:
        return None
    kind = getattr(consumer, "_device_ops_eligible", None)
    if kind is not None:
        return (kind, consumer.index)
    return getattr(consumer, "_device_residency_downstream", None)


def consumer_resident_ok(consumer) -> bool:
    """Should an exchange output bound for ``consumer`` stay resident?
    Yes when residency is enabled, the delivery belongs to a
    device-eligible operator's seam (the placement pass annotated the
    consumer, directly or as that operator's feeder), and — in auto
    mode — the measured placement currently has that operator on
    device, so a host-placed consumer never pays a pointless lazy-fetch
    detour."""
    if not enabled():
        return False
    key = consumer_seam_key(consumer)
    if key is None:
        return False
    if forced():
        return True
    from pathway_tpu.optimize.placement import POLICY

    return POLICY.is_device(*key)


# -- transfer accounting ------------------------------------------------------


def record_h2d(nbytes: int) -> None:
    """Count one host->device transfer of ``nbytes``."""
    _H2D_EVENTS.inc()
    _H2D_BYTES.inc(float(nbytes))


def record_d2h(nbytes: int) -> None:
    """Count one device->host transfer of ``nbytes``."""
    _D2H_EVENTS.inc()
    _D2H_BYTES.inc(float(nbytes))


def record_saved(nbytes: int) -> None:
    """Count ``nbytes`` that stayed resident instead of crossing."""
    if nbytes > 0:
        _SAVED_BYTES.inc(float(nbytes))


# -- the resident batch -------------------------------------------------------

#: Columns slots that trigger transparent materialization when unset
_HOST_SLOTS = frozenset(("cols", "_kbytes", "_kobjs", "_kb_thunk"))


class DeviceResidentColumns(Columns):
    """A :class:`Columns` whose payload bytes live on device.

    ``_dev_rows`` holds the ``(n, W)`` uint8 packed-row matrix (the
    ``collective_exchange`` wire layout: 16-byte key digest | optional
    int64 diff | fixed-width columns); ``_layout`` is the host-side
    ``[(dtype, width), ...]`` schema.  ``n`` and ``diffs`` are eager —
    every delivery path inspects them — while the base class's host
    slots (``cols``/``_kbytes``/``_kobjs``/``_kb_thunk``) stay UNSET
    until :meth:`_materialize` fills them, so any host access routes
    through ``__getattr__`` and fetches the batch bit-exactly.  The
    device buffer survives materialization (a key-forced batch can
    still be re-packed device-side) until :meth:`decay` drops it.
    """

    __slots__ = ("_dev_rows", "_layout", "_has_diffs", "_seam_key", "__weakref__")

    def __init__(
        self,
        dev_rows,
        layout: list,
        has_diffs: bool,
        n: int,
        diffs: "np.ndarray | None" = None,
        seam_key: "tuple | None" = None,
    ) -> None:
        # deliberately NOT calling Columns.__init__: the host slots must
        # stay unset so __getattr__ is the single materialization gate
        self.n = n
        self.diffs = diffs
        self._dev_rows = dev_rows
        self._layout = layout
        self._has_diffs = has_diffs
        self._seam_key = seam_key
        _LIVE_RESIDENT.add(self)
        RESIDENCY_STATS["resident_batches"] += 1

    @classmethod
    def from_device_rows(
        cls,
        dev_rows,
        layout: list,
        has_diffs: bool,
        seam_key: "tuple | None" = None,
    ) -> "DeviceResidentColumns":
        """Wrap a device ``(n, W)`` packed-row matrix.  The diff vector
        is fetched eagerly (8n bytes — the one column every delivery
        path inspects for insert-only screening); keys and value
        columns stay on device."""
        n = int(dev_rows.shape[0])
        diffs = None
        if has_diffs:
            seg = np.asarray(dev_rows[:, 16:24])
            record_d2h(seg.nbytes)
            diffs = np.ascontiguousarray(seg).view(np.int64).ravel()
        return cls(
            dev_rows, layout, has_diffs, n, diffs=diffs, seam_key=seam_key
        )

    # -- transparent host fallback ---------------------------------------

    def __getattr__(self, name: str):
        if name in _HOST_SLOTS:
            self._materialize()
            return object.__getattribute__(self, name)
        raise AttributeError(name)

    def resident(self) -> bool:
        """True while the device buffer is still held."""
        return object.__getattribute__(self, "_dev_rows") is not None

    def _materialized(self) -> bool:
        try:
            object.__getattribute__(self, "cols")
            return True
        except AttributeError:
            return False

    def _materialize(self) -> None:
        """Fetch the packed rows once (one trimmed D2H) and fill the
        base-class slots with exactly what the collective's host path
        would have produced — bit-exact by construction, since both
        decode the same wire layout."""
        if self._materialized():
            return
        dev = self._dev_rows
        if dev is None:  # pragma: no cover — decay always materializes
            raise RuntimeError("resident batch decayed before materializing")
        t0 = _time.perf_counter()
        rows = np.asarray(dev)
        record_d2h(rows.nbytes)
        RESIDENCY_STATS["materializations"] += 1
        kb = np.ascontiguousarray(rows[:, :16])
        off = 16 + (8 if self._has_diffs else 0)
        cols = []
        for dtype, width in self._layout:
            seg = np.ascontiguousarray(rows[:, off : off + width])
            cols.append(seg.view(dtype).ravel())
            off += width
        self._kbytes = kb
        self._kobjs = None
        self._kb_thunk = None
        self.cols = cols
        t1 = _time.perf_counter()
        seam = self._seam_key
        if seam is not None:
            from pathway_tpu.optimize.placement import POLICY

            POLICY.record_seam(
                seam[0], seam[1], self.n, int((t1 - t0) * 1e9)
            )
        trace = _tracing.current()
        if trace is not None:
            trace.span(
                "residency-materialize",
                "exchange",
                t0,
                t1,
                rows=self.n,
                bytes=int(rows.nbytes),
            )

    # -- device-side views -----------------------------------------------

    def device_rows(self):
        """The device ``(n, W)`` packed-row matrix (None once decayed).
        The collective exchange re-packs from this buffer instead of
        uploading host bytes when the batch is repartitioned again."""
        return object.__getattribute__(self, "_dev_rows")

    @property
    def layout(self) -> list:
        return self._layout

    @property
    def has_diffs(self) -> bool:
        return self._has_diffs

    def device_column(self, i: int):
        """Device view of packed column ``i`` (an on-device bitcast of
        the column's byte lanes — no transfer), or ``None`` once the
        buffer decayed.  Bit-identical to ``cols[i]`` by construction:
        both reinterpret the same little-endian bytes."""
        dev = object.__getattribute__(self, "_dev_rows")
        if dev is None:
            return None
        from jax import lax
        from jax.experimental import enable_x64

        dtype, width = self._layout[i]
        off = 16 + (8 if self._has_diffs else 0)
        for j in range(i):
            off += self._layout[j][1]
        seg = dev[:, off : off + width]
        with enable_x64():
            out = lax.bitcast_convert_type(seg, dtype)
            if out.ndim == 2:  # same-width bitcast keeps the byte lane
                out = out.reshape(out.shape[0])
        return out

    def decay(self) -> None:
        """Materialize-if-needed, then drop the device buffer — HBM
        stays bounded by one commit, and anything still referencing the
        batch (deferred state, a snapshot walk) sees plain host data."""
        if object.__getattribute__(self, "_dev_rows") is None:
            return
        self._materialize()
        self._dev_rows = None


def decay_resident_batches() -> None:
    """End-of-commit / pre-persistence hook: materialize and release
    every still-live resident batch (the ``decay_device_batches``
    discipline).  Called from ``device_pipeline.commit_boundary`` and
    the drain seams, so checkpoints never observe device-only state —
    the drain-before-persistence exactly-once invariant."""
    if not _LIVE_RESIDENT:
        return
    for batch in list(_LIVE_RESIDENT):
        batch.decay()
    _LIVE_RESIDENT.clear()


# -- stats --------------------------------------------------------------------


def stats() -> dict:
    """Structured roll-up for bench JSON / cli stats."""
    return {
        "enabled": enabled(),
        "forced": forced(),
        "events": dict(RESIDENCY_STATS),
        "h2d": {
            "events": int(_H2D_EVENTS.value),
            "bytes": int(_H2D_BYTES.value),
        },
        "d2h": {
            "events": int(_D2H_EVENTS.value),
            "bytes": int(_D2H_BYTES.value),
        },
        "bytes_saved": int(_SAVED_BYTES.value),
    }


def reset_counters() -> None:
    """Test/bench helper: zero the event and transfer counters."""
    for key in list(RESIDENCY_STATS):
        RESIDENCY_STATS[key] = 0
    for counter in (
        _H2D_EVENTS,
        _H2D_BYTES,
        _D2H_EVENTS,
        _D2H_BYTES,
        _SAVED_BYTES,
    ):
        counter.value = 0.0
