"""Interpreted engine expression trees.

New implementation of the reference's typed expression interpreter
(reference: src/engine/expression.rs:97-339 — per-row evaluation with error
poisoning; Python escape hatch ``AnyExpression::Apply`` at expression.rs:325).
The Python API lowers its ``ColumnExpression`` DSL to these nodes; evaluation
is per-row with an optional vectorized NumPy fast path applied batch-wise by
the scheduler for numeric columns.

Error semantics: any failing operation or ERROR operand yields ``ERROR``
and reports the failure to the scope's error log instead of raising
(reference: src/engine/error.rs).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.engine.value import ERROR, Error, Json, Pointer, is_error, ref_scalar


class EvalContext:
    """Per-batch evaluation context: collects row-level errors."""

    __slots__ = ("errors",)

    def __init__(self) -> None:
        self.errors: list[tuple[Pointer, str]] = []

    def report(self, key: Pointer, message: str) -> Any:
        self.errors.append((key, message))
        return ERROR


class EngineExpression:
    """Base class; subclasses implement ``evaluate(key, row, ctx)``."""

    __slots__ = ()

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        raise NotImplementedError


class ColumnRef(EngineExpression):
    __slots__ = ("index",)

    def __init__(self, index: int) -> None:
        self.index = index

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        return row[self.index]

    def __repr__(self) -> str:
        return f"col[{self.index}]"


class KeyRef(EngineExpression):
    """The row id (``table.id``)."""

    __slots__ = ()

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        return key


class Const(EngineExpression):
    __slots__ = ("value",)

    def __init__(self, value: Any) -> None:
        self.value = value

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        return self.value

    def __repr__(self) -> str:
        return f"const({self.value!r})"


def _div(a: Any, b: Any) -> Any:
    return a / b


def _floordiv(a: Any, b: Any) -> Any:
    return a // b


def _matmul(a: Any, b: Any) -> Any:
    return np.matmul(a, b)


_BINARY_OPS: dict[str, Callable[[Any, Any], Any]] = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": _div,
    "//": _floordiv,
    "%": lambda a, b: a % b,
    "**": lambda a, b: a**b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "&": lambda a, b: a & b,
    "|": lambda a, b: a | b,
    "^": lambda a, b: a ^ b,
    "<<": lambda a, b: a << b,
    ">>": lambda a, b: a >> b,
    "@": _matmul,
}

_NONE_SAFE_OPS = {"==", "!="}


class Binary(EngineExpression):
    __slots__ = ("op", "left", "right", "fn")

    def __init__(self, op: str, left: EngineExpression, right: EngineExpression) -> None:
        self.op = op
        self.left = left
        self.right = right
        self.fn = _BINARY_OPS[op]

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        a = self.left.evaluate(key, row, ctx)
        b = self.right.evaluate(key, row, ctx)
        if is_error(a) or is_error(b):
            return ERROR
        if (a is None or b is None) and self.op not in _NONE_SAFE_OPS:
            return ctx.report(key, f"cannot apply {self.op} to None operand")
        try:
            return self.fn(a, b)
        except Exception as e:  # noqa: BLE001 — poisoned, not raised
            return ctx.report(key, f"{type(e).__name__} in {self.op}: {e}")

    def __repr__(self) -> str:
        return f"({self.left!r} {self.op} {self.right!r})"


_UNARY_OPS: dict[str, Callable[[Any], Any]] = {
    "-": lambda a: -a,
    "~": lambda a: ~a,
    "not": lambda a: not a,
    "abs": abs,
}


class Unary(EngineExpression):
    __slots__ = ("op", "arg", "fn")

    def __init__(self, op: str, arg: EngineExpression) -> None:
        self.op = op
        self.arg = arg
        self.fn = _UNARY_OPS[op]

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        a = self.arg.evaluate(key, row, ctx)
        if is_error(a):
            return ERROR
        if a is None:
            return ctx.report(key, f"cannot apply unary {self.op} to None")
        try:
            return self.fn(a)
        except Exception as e:  # noqa: BLE001
            return ctx.report(key, f"{type(e).__name__} in unary {self.op}: {e}")


class BooleanChain(EngineExpression):
    """Short-circuit ``&``/``|`` over boolean columns."""

    __slots__ = ("op", "args")

    def __init__(self, op: str, args: Sequence[EngineExpression]) -> None:
        assert op in ("and", "or")
        self.op = op
        self.args = list(args)

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        saw_error = False
        for arg in self.args:
            v = arg.evaluate(key, row, ctx)
            if is_error(v):
                saw_error = True
                continue
            if self.op == "and" and not v:
                return False
            if self.op == "or" and v:
                return True
        if saw_error:
            return ERROR
        return self.op == "and"


class IfElse(EngineExpression):
    __slots__ = ("cond", "then", "otherwise")

    def __init__(
        self, cond: EngineExpression, then: EngineExpression, otherwise: EngineExpression
    ) -> None:
        self.cond = cond
        self.then = then
        self.otherwise = otherwise

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        c = self.cond.evaluate(key, row, ctx)
        if is_error(c):
            return ERROR
        if c is None:
            return ctx.report(key, "if_else condition is None")
        return (self.then if c else self.otherwise).evaluate(key, row, ctx)


class IsNone(EngineExpression):
    __slots__ = ("arg", "negated")

    def __init__(self, arg: EngineExpression, negated: bool = False) -> None:
        self.arg = arg
        self.negated = negated

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        v = self.arg.evaluate(key, row, ctx)
        if is_error(v):
            return ERROR
        return (v is not None) if self.negated else (v is None)


class Coalesce(EngineExpression):
    __slots__ = ("args",)

    def __init__(self, args: Sequence[EngineExpression]) -> None:
        self.args = list(args)

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        for arg in self.args:
            v = arg.evaluate(key, row, ctx)
            if is_error(v):
                return ERROR
            if v is not None:
                return v
        return None


class Require(EngineExpression):
    """``pw.require(val, *deps)`` — None if any dep is None."""

    __slots__ = ("value", "deps")

    def __init__(self, value: EngineExpression, deps: Sequence[EngineExpression]) -> None:
        self.value = value
        self.deps = list(deps)

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        for dep in self.deps:
            v = dep.evaluate(key, row, ctx)
            if is_error(v):
                return ERROR
            if v is None:
                return None
        return self.value.evaluate(key, row, ctx)


class MakeTuple(EngineExpression):
    __slots__ = ("args",)

    def __init__(self, args: Sequence[EngineExpression]) -> None:
        self.args = list(args)

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        out = []
        for arg in self.args:
            v = arg.evaluate(key, row, ctx)
            if is_error(v):
                return ERROR
            out.append(v)
        return tuple(out)


class SequenceGet(EngineExpression):
    __slots__ = ("arg", "index", "default", "checked")

    def __init__(
        self,
        arg: EngineExpression,
        index: EngineExpression,
        default: EngineExpression | None,
        checked: bool,
    ) -> None:
        self.arg = arg
        self.index = index
        self.default = default
        self.checked = checked

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        seq = self.arg.evaluate(key, row, ctx)
        idx = self.index.evaluate(key, row, ctx)
        if is_error(seq) or is_error(idx):
            return ERROR
        try:
            if isinstance(seq, Json):
                got = seq.get(idx, _MISSING)
                if got is _MISSING:
                    raise KeyError(idx)
                return got
            return seq[idx]
        except Exception as e:  # noqa: BLE001
            if self.checked:
                return (
                    self.default.evaluate(key, row, ctx)
                    if self.default is not None
                    else None
                )
            return ctx.report(key, f"index error: {e}")


_MISSING = object()


class JsonGet(EngineExpression):
    """``col.get("field")`` / ``col["field"]`` over Json values."""

    __slots__ = ("arg", "index", "default", "checked")

    def __init__(
        self,
        arg: EngineExpression,
        index: EngineExpression,
        default: EngineExpression | None = None,
        checked: bool = True,
    ) -> None:
        self.arg = arg
        self.index = index
        self.default = default
        self.checked = checked

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        val = self.arg.evaluate(key, row, ctx)
        idx = self.index.evaluate(key, row, ctx)
        if is_error(val) or is_error(idx):
            return ERROR
        if not isinstance(val, Json):
            val = Json(val)
        got = val.get(idx, _MISSING)
        if got is _MISSING:
            if self.checked:
                return (
                    self.default.evaluate(key, row, ctx)
                    if self.default is not None
                    else None
                )
            return ctx.report(key, f"json key {idx!r} not found")
        return got


class Cast(EngineExpression):
    __slots__ = ("arg", "target")

    _CASTS: dict[str, Callable[[Any], Any]] = {
        "Int": int,
        "Float": float,
        "Bool": bool,
        "String": str,
    }

    def __init__(self, arg: EngineExpression, target: str) -> None:
        self.arg = arg
        self.target = target

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        v = self.arg.evaluate(key, row, ctx)
        if is_error(v):
            return ERROR
        if v is None:
            return None
        try:
            return self._CASTS[self.target](v)
        except Exception as e:  # noqa: BLE001
            return ctx.report(key, f"cannot cast {v!r} to {self.target}: {e}")


class Convert(EngineExpression):
    """Json → typed value conversion (``.as_int()`` etc.)."""

    __slots__ = ("arg", "target", "unwrap")

    def __init__(self, arg: EngineExpression, target: str, unwrap: bool = False) -> None:
        self.arg = arg
        self.target = target
        self.unwrap = unwrap

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        v = self.arg.evaluate(key, row, ctx)
        if is_error(v):
            return ERROR
        if v is None:
            return None
        if not isinstance(v, Json):
            v = Json(v)
        inner = v.value
        ok: Any = None
        if self.target == "Int" and isinstance(inner, (int, float)) and not isinstance(inner, bool):
            ok = int(inner)
        elif self.target == "Float" and isinstance(inner, (int, float)) and not isinstance(inner, bool):
            ok = float(inner)
        elif self.target == "Bool" and isinstance(inner, bool):
            ok = inner
        elif self.target == "String" and isinstance(inner, str):
            ok = inner
        elif self.target == "List" and isinstance(inner, list):
            ok = tuple(inner)
        if ok is None and not (inner is None and not self.unwrap):
            return ctx.report(key, f"cannot convert json {inner!r} to {self.target}")
        return ok


class Unwrap(EngineExpression):
    __slots__ = ("arg",)

    def __init__(self, arg: EngineExpression) -> None:
        self.arg = arg

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        v = self.arg.evaluate(key, row, ctx)
        if is_error(v):
            return ERROR
        if v is None:
            return ctx.report(key, "unwrap() on None value")
        return v


class FillError(EngineExpression):
    """``pw.fill_error(expr, fallback)`` (reference: expression.rs FillError)."""

    __slots__ = ("arg", "fallback")

    def __init__(self, arg: EngineExpression, fallback: EngineExpression) -> None:
        self.arg = arg
        self.fallback = fallback

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        # evaluate in a throwaway context: errors here are being handled
        sub = EvalContext()
        v = self.arg.evaluate(key, row, sub)
        if is_error(v):
            return self.fallback.evaluate(key, row, ctx)
        return v


class Apply(EngineExpression):
    """Python function escape hatch (AnyExpression::Apply, expression.rs:325)."""

    __slots__ = ("fn", "args", "propagate_none", "deterministic")

    def __init__(
        self,
        fn: Callable[..., Any],
        args: Sequence[EngineExpression],
        propagate_none: bool = False,
        deterministic: bool = True,
    ) -> None:
        self.fn = fn
        self.args = list(args)
        self.propagate_none = propagate_none
        self.deterministic = deterministic

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        vals = []
        for arg in self.args:
            v = arg.evaluate(key, row, ctx)
            if is_error(v):
                return ERROR
            if v is None and self.propagate_none:
                return None
            vals.append(v)
        try:
            return self.fn(*vals)
        except Exception as e:  # noqa: BLE001
            return ctx.report(key, f"{type(e).__name__} in apply: {e}")


class PointerFrom(EngineExpression):
    """``table.pointer_from(*cols, instance=...)``."""

    __slots__ = ("args", "instance")

    def __init__(
        self, args: Sequence[EngineExpression], instance: EngineExpression | None = None
    ) -> None:
        self.args = list(args)
        self.instance = instance

    def evaluate(self, key: Pointer, row: tuple, ctx: EvalContext) -> Any:
        vals = []
        for arg in self.args:
            v = arg.evaluate(key, row, ctx)
            if is_error(v):
                return ERROR
            vals.append(v)
        inst = None
        if self.instance is not None:
            inst = self.instance.evaluate(key, row, ctx)
            if is_error(inst):
                return ERROR
        return ref_scalar(*vals, instance=inst)


def evaluate_expressions(
    expressions: Sequence[EngineExpression],
    key: Pointer,
    row: tuple,
    ctx: EvalContext,
) -> tuple:
    return tuple(expr.evaluate(key, row, ctx) for expr in expressions)
