"""Temporal engine operators: event-time behaviors, session windows,
interval / asof / asof-now joins.

Reference: src/engine/dataflow/operators/time_column.rs (postpone_core :380
= buffer, TimeColumnForget :556, TimeColumnFreeze/ignore_late :631,677) and
the temporal joins built on them (stdlib lowering). The event-time
"current time" is the watermark = max value of the designated time column
seen so far, exactly the reference's SelfCompactionTime notion (:54) —
logical commit times order delivery, the time column orders the data.

All operators recompute per affected instance-group on change (the same
local-recomputation strategy the rest of the engine uses), which preserves
the incremental output contract without differential arrangements.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Sequence

import heapq

from pathway_tpu.engine.batch import DeltaBatch
from pathway_tpu.engine.graph import (
    Node,
    Scope,
    emit_local_group_diffs,
    join_result_key,
)
from pathway_tpu.engine.value import Pointer, is_error


def _watermark_update(current: Any, batch: DeltaBatch, time_col: int) -> Any:
    for _key, row, diff in batch:
        if diff <= 0:
            continue
        t = row[time_col]
        if t is None or is_error(t):
            continue
        if current is None or t > current:
            current = t
    return current


class BufferNode(Node):
    """Postpone rows until the watermark reaches their threshold column
    (reference: postpone_core time_column.rs:380; backs behavior ``delay``).

    ``flush_on_end``: release everything when the stream finishes (the
    reference flushes buffers at end-of-input in batch mode).
    """

    def __init__(
        self,
        scope: Scope,
        source: Node,
        threshold_col: int,
        time_col: int,
        flush_on_end: bool = True,
    ) -> None:
        super().__init__(scope, [source], source.arity)
        self.threshold_col = threshold_col
        self.time_col = time_col
        self.flush_on_end = flush_on_end
        self.watermark: Any = None
        self.held: dict[Pointer, tuple] = {}
        # release heap (threshold, seq, key) with lazy invalidation, so each
        # commit costs O(released·log n), not O(held)
        self._heap: list[tuple[Any, int, Pointer]] = []
        self._seq = 0
        self._ended = False

    STATE_ATTRS = ("watermark", "held", "_heap", "_seq", "_ended")

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        self.watermark = _watermark_update(self.watermark, batch, self.time_col)
        out = DeltaBatch()
        for key, row, diff in batch:
            if diff < 0:
                if key in self.held:
                    del self.held[key]
                else:
                    out.append(key, row, diff)
                continue
            threshold = row[self.threshold_col]
            if (
                self._ended
                or threshold is None
                or is_error(threshold)
                or (self.watermark is not None and threshold <= self.watermark)
            ):
                out.append(key, row, diff)
            else:
                self.held[key] = row
                heapq.heappush(
                    self._heap, (threshold, self._next_seq(), key)
                )
        if self.watermark is not None:
            while self._heap and self._heap[0][0] <= self.watermark:
                _thr, _seq, k = heapq.heappop(self._heap)
                row = self.held.pop(k, None)
                if row is not None:
                    out.append(k, row, 1)
        return out.consolidate()

    def on_end(self) -> None:
        self._ended = True
        if self.flush_on_end and self.held:
            out = DeltaBatch((k, r, 1) for k, r in self.held.items())
            self.held.clear()
            # inject as pending so a final commit picks it up
            self.push_self(out)

    def push_self(self, batch: DeltaBatch) -> None:
        self.pending.setdefault(-1, []).append(batch)

    def take(self, port: int) -> DeltaBatch:
        merged = super().take(port)
        extra = self.pending.pop(-1, None)
        if extra:
            # NEVER extend the taken batch in place: take() may hand back
            # the producer's own batch object (or its consolidate cache),
            # still aliased by sibling consumers' pending queues and the
            # producer's deferred state lag
            out = DeltaBatch(merged.entries)
            for b in extra:
                out.extend(b)
            return out
        return merged


class ForgetNode(Node):
    """Retract rows once the watermark passes their threshold column; drop
    late arrivals (reference: TimeColumnForget time_column.rs:556; backs
    behavior ``cutoff``).

    ``mark_forgetting_records`` appends a bool column marking forgetting
    retractions (reference forget :2662 mark_forgetting_records).
    """

    def __init__(
        self,
        scope: Scope,
        source: Node,
        threshold_col: int,
        time_col: int,
        mark_forgetting_records: bool = False,
    ) -> None:
        arity = source.arity + (1 if mark_forgetting_records else 0)
        super().__init__(scope, [source], arity)
        self.threshold_col = threshold_col
        self.time_col = time_col
        self.mark = mark_forgetting_records
        self.watermark: Any = None
        self.live: dict[Pointer, tuple] = {}
        self._heap: list[tuple[Any, int, Pointer]] = []
        self._seq = 0

    STATE_ATTRS = ("watermark", "live", "_heap", "_seq")

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _emit(self, out: DeltaBatch, key: Pointer, row: tuple, diff: int, forgetting: bool) -> None:
        if self.mark:
            row = row + (forgetting,)
        out.append(key, row, diff)

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        # Lateness is judged against the watermark as of the *previous*
        # commit: entries simultaneous with the watermark-advancing row are
        # processed using the last recorded time (reference
        # temporal_behavior.py docstring; ADVICE r1). The watermark advances
        # after the row loop, before the expiry sweep.
        out = DeltaBatch()
        for key, row, diff in batch:
            threshold = row[self.threshold_col]
            late = (
                self.watermark is not None
                and threshold is not None
                and not is_error(threshold)
                and threshold <= self.watermark
            )
            if diff < 0:
                if key in self.live:
                    del self.live[key]
                    self._emit(out, key, row, diff, False)
                continue
            if late:
                continue  # dropped: arrived after its cutoff
            self.live[key] = row
            if threshold is not None and not is_error(threshold):
                heapq.heappush(self._heap, (threshold, self._next_seq(), key))
            self._emit(out, key, row, diff, False)
        self.watermark = _watermark_update(self.watermark, batch, self.time_col)
        # forget everything whose threshold passed (lazy heap: stale entries
        # for deleted/re-added keys are skipped via the live-row check)
        if self.watermark is not None:
            while self._heap and self._heap[0][0] <= self.watermark:
                _thr, _seq, k = heapq.heappop(self._heap)
                r = self.live.get(k)
                if r is not None and r[self.threshold_col] <= self.watermark:
                    del self.live[k]
                    self._emit(out, k, r, -1, True)
        return out.consolidate()


class FreezeNode(Node):
    """Drop updates (inserts and deletes) to frozen times: once the
    watermark passes a row's threshold, that region is immutable
    (reference: TimeColumnFreeze time_column.rs:631)."""

    STATE_ATTRS = ("watermark",)

    def __init__(
        self, scope: Scope, source: Node, threshold_col: int, time_col: int
    ) -> None:
        super().__init__(scope, [source], source.arity)
        self.threshold_col = threshold_col
        self.time_col = time_col
        self.watermark: Any = None

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        out = DeltaBatch()
        for key, row, diff in batch:
            threshold = row[self.threshold_col]
            frozen = (
                self.watermark is not None
                and threshold is not None
                and not is_error(threshold)
                and threshold <= self.watermark
            )
            if not frozen:
                out.append(key, row, diff)
        self.watermark = _watermark_update(self.watermark, batch, self.time_col)
        return out.consolidate()


class SessionAssignNode(Node):
    """Assign (session_start, session_end) per row: rows of one instance
    whose gap exceeds ``max_gap`` start a new session. Output row =
    input row + (start, end), keyed by source key; affected instances are
    recomputed locally (reference: session windows _window.py:593+)."""

    STATE_ATTRS = ("members",)

    def __init__(
        self,
        scope: Scope,
        source: Node,
        time_col: int,
        instance_col: int | None,
        max_gap: Any,
    ) -> None:
        super().__init__(scope, [source], source.arity + 2)
        self.time_col = time_col
        self.instance_col = instance_col
        self.max_gap = max_gap
        self.members: dict[Any, dict[Pointer, tuple]] = {}

    def _inst(self, row: tuple) -> Any:
        if self.instance_col is None:
            return None
        v = row[self.instance_col]
        try:
            hash(v)
        except TypeError:
            v = repr(v)
        return v

    def _local(self, inst: Any) -> dict[Pointer, tuple]:
        rows = self.members.get(inst, {})
        items = sorted(rows.items(), key=lambda kv: (kv[1][self.time_col], int(kv[0])))
        out: dict[Pointer, tuple] = {}
        # split into sessions by gap
        session: list[tuple[Pointer, tuple]] = []

        def flush() -> None:
            if not session:
                return
            start = session[0][1][self.time_col]
            end = session[-1][1][self.time_col]
            for k, r in session:
                out[k] = r + (start, end)
            session.clear()

        prev_t = None
        for k, r in items:
            t = r[self.time_col]
            if prev_t is not None and t - prev_t > self.max_gap:
                flush()
            session.append((k, r))
            prev_t = t
        flush()
        return out

    def process(self, time: int) -> DeltaBatch:
        batch = self.take(0)
        old: dict[Any, dict[Pointer, tuple]] = {}
        for key, row, diff in batch:
            t = row[self.time_col]
            if t is None or is_error(t):
                self.report(key, "error/None time value in session window")
                continue
            inst = self._inst(row)
            if inst not in old:
                old[inst] = self._local(inst)
            group = self.members.setdefault(inst, {})
            if diff > 0:
                group[key] = row
            else:
                group.pop(key, None)
                if not group:
                    self.members.pop(inst, None)
        out = DeltaBatch()
        emit_local_group_diffs(out, old, self._local)
        return out.consolidate()


class IntervalJoinNode(Node):
    """t_right ∈ [t_left + lower, t_left + upper] equi-instance join
    (reference: stdlib/temporal/_interval_join.py over engine buffers).

    Output = left_row + right_row (+ padding on outer kinds), keyed like the
    hash join. Per-instance local recomputation keeps it incremental.
    """

    STATE_ATTRS = ("left_rows", "right_rows")

    def __init__(
        self,
        scope: Scope,
        left: Node,
        right: Node,
        left_time_col: int,
        right_time_col: int,
        lower_bound: Any,
        upper_bound: Any,
        left_instance_col: int | None = None,
        right_instance_col: int | None = None,
        kind: str = "inner",
    ) -> None:
        super().__init__(scope, [left, right], left.arity + right.arity)
        self.lt = left_time_col
        self.rt = right_time_col
        self.lo = lower_bound
        self.hi = upper_bound
        self.li = left_instance_col
        self.ri = right_instance_col
        self.kind = kind
        self.left_rows: dict[Any, dict[Pointer, tuple]] = {}
        self.right_rows: dict[Any, dict[Pointer, tuple]] = {}

    def _inst(self, row: tuple, col: int | None) -> Any:
        if col is None:
            return None
        v = row[col]
        try:
            hash(v)
        except TypeError:
            v = repr(v)
        return v

    def _local(self, inst: Any) -> dict[Pointer, tuple]:
        lrows = self.left_rows.get(inst, {})
        rrows = self.right_rows.get(inst, {})
        out: dict[Pointer, tuple] = {}
        r_sorted = sorted(
            rrows.items(), key=lambda kv: (kv[1][self.rt], int(kv[0]))
        )
        r_times = [kv[1][self.rt] for kv in r_sorted]
        l_pad = (None,) * self.inputs[0].arity
        r_pad = (None,) * self.inputs[1].arity
        matched_right: set[Pointer] = set()
        for lk, lrow in lrows.items():
            t = lrow[self.lt]
            lo_i = bisect.bisect_left(r_times, t + self.lo)
            hi_i = bisect.bisect_right(r_times, t + self.hi)
            if lo_i == hi_i:
                if self.kind in ("left", "outer"):
                    out[join_result_key(lk, None)] = lrow + r_pad
                continue
            for rk, rrow in r_sorted[lo_i:hi_i]:
                matched_right.add(rk)
                out[join_result_key(lk, rk)] = lrow + rrow
        if self.kind in ("right", "outer"):
            for rk, rrow in rrows.items():
                if rk not in matched_right:
                    out[join_result_key(None, rk)] = l_pad + rrow
        return out

    def process(self, time: int) -> DeltaBatch:
        left_batch = self.take(0)
        right_batch = self.take(1)
        old: dict[Any, dict[Pointer, tuple]] = {}

        def note(inst: Any) -> None:
            if inst not in old:
                old[inst] = self._local(inst)

        staged = []
        for key, row, diff in left_batch:
            if is_error(row[self.lt]) or row[self.lt] is None:
                self.report(key, "error/None time in interval join (left)")
                continue
            inst = self._inst(row, self.li)
            note(inst)
            staged.append((0, inst, key, row, diff))
        for key, row, diff in right_batch:
            if is_error(row[self.rt]) or row[self.rt] is None:
                self.report(key, "error/None time in interval join (right)")
                continue
            inst = self._inst(row, self.ri)
            note(inst)
            staged.append((1, inst, key, row, diff))
        for side, inst, key, row, diff in staged:
            arr = self.left_rows if side == 0 else self.right_rows
            group = arr.setdefault(inst, {})
            if diff > 0:
                group[key] = row
            else:
                group.pop(key, None)
                if not group:
                    arr.pop(inst, None)
        out = DeltaBatch()
        emit_local_group_diffs(out, old, self._local)
        return out.consolidate()


class AsofJoinNode(Node):
    """For each left row, the closest right row at-or-before its time
    (per instance; ``direction`` backward/forward/nearest). Keyed by the
    left row id (reference: stdlib/temporal/_asof_join.py)."""

    STATE_ATTRS = ("left_rows", "right_rows")

    def __init__(
        self,
        scope: Scope,
        left: Node,
        right: Node,
        left_time_col: int,
        right_time_col: int,
        left_instance_col: int | None = None,
        right_instance_col: int | None = None,
        direction: str = "backward",
        kind: str = "inner",
    ) -> None:
        if direction not in ("backward", "forward", "nearest"):
            raise ValueError(
                f"asof direction must be backward/forward/nearest, got {direction!r}"
            )
        super().__init__(scope, [left, right], left.arity + right.arity)
        self.lt = left_time_col
        self.rt = right_time_col
        self.li = left_instance_col
        self.ri = right_instance_col
        self.direction = direction
        self.kind = kind
        self.left_rows: dict[Any, dict[Pointer, tuple]] = {}
        self.right_rows: dict[Any, dict[Pointer, tuple]] = {}

    _inst = IntervalJoinNode._inst

    def _match_index(self, t: Any, r_sorted: list, r_times: list) -> int | None:
        if not r_times:
            return None
        if self.direction == "backward":
            i = bisect.bisect_right(r_times, t) - 1
            return i if i >= 0 else None
        if self.direction == "forward":
            i = bisect.bisect_left(r_times, t)
            return i if i < len(r_sorted) else None
        # nearest
        i = bisect.bisect_right(r_times, t) - 1
        j = bisect.bisect_left(r_times, t)
        cands = [c for c in (i, j) if 0 <= c < len(r_sorted)]
        if not cands:
            return None
        return min(cands, key=lambda c: abs(r_sorted[c][1][self.rt] - t))

    def _local(self, inst: Any) -> dict[Pointer, tuple]:
        lrows = self.left_rows.get(inst, {})
        rrows = self.right_rows.get(inst, {})
        r_sorted = sorted(
            rrows.items(), key=lambda kv: (kv[1][self.rt], int(kv[0]))
        )
        r_times = [kv[1][self.rt] for kv in r_sorted]
        l_pad = (None,) * self.inputs[0].arity
        r_pad = (None,) * self.inputs[1].arity
        out: dict[Pointer, tuple] = {}
        matched_right: set[int] = set()
        for lk, lrow in lrows.items():
            idx = self._match_index(lrow[self.lt], r_sorted, r_times)
            if idx is not None:
                matched_right.add(idx)
                out[lk] = lrow + r_sorted[idx][1]
            elif self.kind in ("left", "outer"):
                out[lk] = lrow + r_pad
        if self.kind in ("right", "outer"):
            for i, (rk, rrow) in enumerate(r_sorted):
                if i not in matched_right:
                    out[join_result_key(None, rk)] = l_pad + rrow
        return out

    process = IntervalJoinNode.process
    # note: process uses self.lt/self.rt/self.li/self.ri/_local identically


class AsofNowJoinNode(Node):
    """Left rows join the right side's state as of their arrival; results
    never revise when the right side changes later — deletion of the left
    row retracts its result (reference: _asof_now_join.py:403, built on the
    gradual-broadcast machinery; same contract as the external index)."""

    STATE_ATTRS = ("right_index", "answered")

    def __init__(
        self,
        scope: Scope,
        left: Node,
        right: Node,
        left_on: Sequence[int],
        right_on: Sequence[int],
        kind: str = "inner",
    ) -> None:
        super().__init__(scope, [left, right], left.arity + right.arity)
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.kind = kind
        self.right_index: dict[Any, dict[Pointer, tuple]] = {}
        self.answered: dict[Pointer, list[tuple[Pointer, tuple]]] = {}

    def _jk(self, row: tuple, cols: Sequence[int]) -> Any:
        vals = tuple(row[c] for c in cols)
        try:
            hash(vals)
        except TypeError:
            vals = tuple(repr(v) for v in vals)
        return vals

    def process(self, time: int) -> DeltaBatch:
        left_batch = self.take(0)
        right_batch = self.take(1)
        # 1. fold right side state
        for key, row, diff in right_batch:
            jk = self._jk(row, self.right_on)
            group = self.right_index.setdefault(jk, {})
            if diff > 0:
                group[key] = row
            else:
                group.pop(key, None)
                if not group:
                    self.right_index.pop(jk, None)
        # 2. answer left arrivals as-of-now
        out = DeltaBatch()
        r_pad = (None,) * self.inputs[1].arity
        for key, row, diff in left_batch:
            if diff < 0:
                for okey, orow in self.answered.pop(key, ()):  # retract
                    out.append(okey, orow, -1)
                continue
            jk = self._jk(row, self.left_on)
            matches = self.right_index.get(jk, {})
            emitted: list[tuple[Pointer, tuple]] = []
            if matches:
                for rk, rrow in matches.items():
                    okey = join_result_key(key, rk)
                    orow = row + rrow
                    out.append(okey, orow, 1)
                    emitted.append((okey, orow))
            elif self.kind in ("left", "outer"):
                orow = row + r_pad
                out.append(key, orow, 1)
                emitted.append((key, orow))
            prev = self.answered.get(key)
            if prev:
                for okey, orow in prev:
                    out.append(okey, orow, -1)
            self.answered[key] = emitted
        return out.consolidate()


class GradualBroadcastNode(Node):
    """Attach an ``apx_value`` column that moves between ``lower`` and
    ``upper`` per row as the broadcast value advances.

    Reference: operators/gradual_broadcast.rs — the threshold stream's
    (lower, value, upper) triplet maps to a key-space cutoff at fraction
    (value - lower) / (upper - lower); rows whose key falls below the
    cutoff see ``upper``, the rest see ``lower``. As ``value`` moves, only
    the rows crossing the moving cutoff re-emit — a gradual, incremental
    broadcast instead of an all-at-once update (used by louvain's
    randomized move acceptance).
    """

    STATE_ATTRS = ("triplet",)

    _KEY_SPACE = float(2**64)

    def __init__(self, scope: Scope, source: Node, threshold: Node) -> None:
        super().__init__(scope, [source, threshold], source.arity + 1)
        self.triplet: tuple | None = None  # (lower, value, upper)

    def _fraction(self, key: Pointer) -> float:
        return (int(key) % 2**64) / self._KEY_SPACE

    def _apx(self, key: Pointer) -> Any:
        if self.triplet is None:
            return None
        lower, value, upper = self.triplet
        if upper == lower:
            return lower
        cutoff = (value - lower) / (upper - lower)
        return upper if self._fraction(key) <= cutoff else lower

    def process(self, time: int) -> DeltaBatch:
        src_batch = self.take(0)
        thr_batch = self.take(1)
        out = DeltaBatch()
        retracted: set[Pointer] = set()

        def retract(key: Pointer) -> None:
            # each key's previous output may be retracted at most once per
            # commit, however many branches touch it
            prev = self.current.get(key)
            if prev is not None and key not in retracted:
                out.append(key, prev, -1)
                retracted.add(key)

        old_triplet = self.triplet
        for _key, row, diff in thr_batch:
            if diff > 0:
                self.triplet = (row[0], row[1], row[2])
        handled = {key for key, _r, _d in src_batch}
        if self.triplet != old_triplet:
            # re-evaluate rows already emitted; only cutoff-crossers change;
            # keys updated in this commit are covered by the source loop
            for key, cur in list(self.current.items()):
                if key in handled:
                    continue
                new_apx = self._apx(key)
                if cur[-1] != new_apx:
                    retract(key)
                    # own stored row (minus apx) — the input replica's
                    # current may hold only one shard under multi-worker
                    out.append(key, cur[:-1] + (new_apx,), 1)
        for key, row, diff in src_batch:
            retract(key)
            if diff > 0:
                out.append(key, row + (self._apx(key),), 1)
        return out.consolidate()
