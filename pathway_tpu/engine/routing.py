"""Pure shard-routing kernel: vectorized worker assignment for columnar batches.

Extracted from the sharded scheduler so the routing math is directly
testable and shared by BOTH exchange paths — the in-process lockstep
scheduler (engine/sharded.py) and the multiprocess TCP mesh
(engine/distributed.py) call the same :func:`columnar_shards`, so a row can
never land on a different worker depending on which transport carried it.

The contract mirrors the reference's exchange pacts (timely exchange
channels partition records by a hash of the key, never a per-row
interpreted loop): given a partition rule from
:func:`pathway_tpu.engine.sharded.partition_rule` and a
:class:`~pathway_tpu.engine.batch.Columns` payload, produce an int64 worker
id per row — or ``None`` whenever the vectorized assignment cannot be
digest-identical to the per-row partitioners, in which case the caller
falls back to the row path. The kernel never raises on data it cannot
handle; ``None`` IS the error channel.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Sequence

import numpy as np

from pathway_tpu.engine.value import Pointer, hash_values, hash_values_batch

if TYPE_CHECKING:  # pragma: no cover
    from pathway_tpu.engine.batch import Columns

__all__ = [
    "columnar_shards",
    "mod_u128_bytes",
    "shards_of_values",
]


def _shard_of(value: Any, n: int) -> int:
    """Per-row worker assignment — THE definition of which worker owns a
    value; everything vectorized below must agree with it bit for bit."""
    if isinstance(value, Pointer):
        return int(value) % n
    try:
        return int(hash_values((value,), salt=b"shard")) % n
    except TypeError:
        return int(hash_values((repr(value),), salt=b"shard")) % n


def mod_u128_bytes(kb: np.ndarray, n: int) -> np.ndarray:
    """Vectorized ``int.from_bytes(row, "little") % n`` over an ``(m, 16)``
    uint8 matrix of little-endian 128-bit integers (key digests).

    The halves fold via ``(hi * 2**64 + lo) % n ==
    ((hi % n) * (2**64 % n) + lo % n) % n``; every intermediate stays below
    ``n**2``, so the arithmetic is uint64-exact for any realistic worker
    count (n < 2**32)."""
    kb = np.ascontiguousarray(kb)
    lo = kb[:, :8].copy().view(np.uint64).ravel()
    hi = kb[:, 8:].copy().view(np.uint64).ravel()
    nn = np.uint64(n)
    base = np.uint64((1 << 64) % n)
    return (((hi % nn) * base + lo % nn) % nn).astype(np.int64)


def shards_of_values(values: Sequence[Any], n: int) -> np.ndarray:
    """Batched ``_shard_of``: one :func:`hash_values_batch` call builds the
    digest matrix for every non-Pointer value, one vectorized mod folds it
    to worker ids. Callers pass DISTINCT representatives (factorize
    output), so the remaining Python loop runs per distinct key inside a
    single call — not per row on the exchange hot path."""
    shards = np.empty(len(values), np.int64)
    rows: list[tuple] = []
    where: list[int] = []
    for i, v in enumerate(values):
        if isinstance(v, Pointer):
            shards[i] = int(v) % n
        else:
            rows.append((v,))
            where.append(i)
    if rows:
        kb = hash_values_batch(rows, salt=b"shard", on_type_error="repr")
        shards[np.asarray(where, np.int64)] = mod_u128_bytes(kb, n)
    return shards


def _object_codes(col: np.ndarray) -> np.ndarray:
    """Dense int64 codes for a non-sortable (object-dtype) column, keyed
    by the value's hash_values DIGEST — the exact identity the per-row
    partitioners use. Dict equality would be coarser (a tz-aware datetime
    equals its rebased twin but digests differently), which could route
    one logical key to different workers depending on which class member
    a batch sees first.

    One ``hash_values_batch`` call computes every digest; the codes come
    from a single ``np.unique`` over the digest matrix. (Code order
    differs from first-seen order, which is fine: ``factorize_multi``
    consumes only the identity classes, never the code values.)"""
    kb = hash_values_batch(
        [(v,) for v in col.tolist()], on_type_error="repr"
    )
    _uniq, inverse = np.unique(kb, axis=0, return_inverse=True)
    return inverse.ravel().astype(np.int64, copy=False)


def columnar_shards(
    rule: tuple, columns: "Columns", n: int
) -> np.ndarray | None:
    """Vectorized worker assignment for a columnar batch, or ``None`` when
    the routing rule needs the row path.

    Digest-identical to the per-row partitioners (engine/sharded.py):
    row-key routing is the full 128-bit pointer mod n; column routing
    hashes per DISTINCT value (``factorize_multi``) and maps back through
    the inverse index. Fallback rules (→ ``None``, never an exception):

    - ``("pin",)`` rules — the caller pushes the whole batch to worker 0
      without consulting a shard table;
    - float columns containing NaN — ``np.unique`` collapses
      distinct-bit NaNs that the per-row digests keep apart;
    - column dtypes outside bool/int/float/unicode/object;
    - key-bytes derivation failure for ``("key",)`` batches.
    """
    kind = rule[0]
    if kind in ("cols", "col"):
        if kind == "cols":
            idxs = list(rule[1])
            if len(idxs) == 0:
                return np.full(columns.n, _shard_of((), n), np.int64)
            bare = False  # by_cols hashes the value TUPLE
        else:
            c = rule[1]
            if c is None:
                return np.full(columns.n, _shard_of(None, n), np.int64)
            idxs = [c]
            bare = True  # by_col hashes the bare value
        from pathway_tpu.engine.device import factorize_multi

        arrays = []
        for c in idxs:
            col = columns.cols[c]
            if col.dtype.kind in "bifU":
                if col.dtype.kind == "f" and np.isnan(col).any():
                    return None
                arrays.append(col)
            elif col.dtype == object:
                arrays.append(_object_codes(col))
            else:
                return None
        first, inverse = factorize_multi(arrays)
        reps = zip(*(columns.cols[c][first].tolist() for c in idxs))
        if bare:
            table = shards_of_values([t[0] for t in reps], n)
        else:
            table = shards_of_values(list(reps), n)
        return table[inverse]
    if kind != "key":
        return None  # "pin" never reaches a shard table (fn is None earlier)
    try:
        kb = columns.kbytes()
    except Exception:  # lazy key thunk failed: the row path derives keys
        return None
    return mod_u128_bytes(kb, n)
